"""ResidentState: device-resident cluster tensors + incremental encode.

Every scheduling cycle used to rebuild the entire SolverBatch from Python
objects (ops/tensors.encode_batch) — BENCH_r05 shows that re-encode is
now the wall: the solve is pipelined and mesh-sharded while the encoder
feeds it at a fraction of the host budget.  The reference control plane
never rebuilds: informers deliver deltas (PAPER.md L3).  This module
mirrors that on the solver plane:

  * the cluster/placement-side tensors (the exact arrays ops/solver's
    dispatch consumes, named per ops/tensors.FIELD_DTYPES) live as
    FROZEN copy-on-write numpy masters BETWEEN cycles, advanced by
    coalesced watch-event deltas (resident/deltas.py) — a capacity flap
    recomputes one cluster's lanes, not 5000 clusters' worth of Python;
  * their device mirrors stay resident too: jitted scatter kernels
    (ops/resident_update.py) rewrite only the churned lanes in place and
    the result is primed into the solver's device-transfer cache
    (ops/solver.prime_cluster_slot), so a steady-state dispatch moves
    only the cycle's binding rows — pjit inputs already placed to match
    the meshing PartitionSpecs skip the repartition (SNIPPETS [1]/[3]);
  * per-binding encoded rows are cached in a slot store keyed by
    (namespace/name, resourceVersion) under a structural generation —
    the policy/placement side of the key rides the resourceVersion (any
    spec or status write bumps it) plus the process-wide plugin-registry
    generation; a cycle re-encodes ONLY churned bindings and gathers the
    rest with vectorized fancy indexing.

Misses are not re-implemented: they run through the REAL encode_batch on
the miss subset, and the resulting mini-batch is merged by translating
its (placement, class, GVK, resource) vocabulary into the resident one —
row contents are bit-identical by construction, ids are remapped.  The
same property makes the fallback lossless: any structural change
(cluster membership/spec/labels, plugin registry, C-padding growth, a
failed audit) resets the plane and the next cycle is one full
encode_batch whose tensors are adopted as the new resident masters.

Safety is first-class: a periodic audit re-encodes the cycle from
scratch and compares the resident batch BIT-EXACT (vocabulary-mapped —
resident axes may hold retired entries; every value a solve can read
must match).  A mismatch increments karmada_resident_audits_total
{outcome="mismatch"}, forces a rebuild, and the fresh batch serves the
cycle.  /debug/resident and the resident.* flight-recorder spans expose
generation, vocabulary sizes, hit rate, delta depth and audit outcomes.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karmada_tpu import chaos as chaos_mod
from karmada_tpu import obs
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.work import ResourceBindingStatus
from karmada_tpu.ops import serial, tensors
from karmada_tpu.resident.deltas import (
    API,
    CAPACITY,
    STRUCTURAL,
    CycleDeltas,
    _RANK,
    classify_change,
)
from karmada_tpu.utils.metrics import REGISTRY

# -- observability ------------------------------------------------------------
RESIDENT_GENERATION = REGISTRY.gauge(
    "karmada_resident_generation",
    "Structural generation of the resident state plane (bumps on every "
    "full rebuild; 0 = no plane adopted yet)",
)
RESIDENT_VOCAB = REGISTRY.gauge(
    "karmada_resident_vocab_size",
    "Entries per resident vocabulary axis",
    ("axis",),
)
RESIDENT_ROWS = REGISTRY.gauge(
    "karmada_resident_rows_cached",
    "Encoded binding rows currently cached in the resident slot store",
)
RESIDENT_LOOKUPS = REGISTRY.counter(
    "karmada_resident_row_lookups_total",
    "Per-binding row-cache lookups by result (hit = gathered from the "
    "resident store, miss = re-encoded via encode_batch)",
    ("result",),
)
RESIDENT_REBUILDS = REGISTRY.counter(
    "karmada_resident_rebuilds_total",
    "Full resident-plane rebuilds (lossless fallback to encode_batch) "
    "by reason",
    ("reason",),
)
RESIDENT_AUDITS = REGISTRY.counter(
    "karmada_resident_audits_total",
    "Resident-vs-full-encode parity audits by outcome (a mismatch "
    "forces a rebuild and the fresh batch serves the cycle)",
    ("outcome",),
)
RESIDENT_DELTAS = REGISTRY.counter(
    "karmada_resident_cluster_deltas_total",
    "Coalesced cluster deltas applied to the resident plane by class",
    ("kind",),
)
RESIDENT_GATHER_FALLBACKS = REGISTRY.counter(
    "karmada_resident_gather_fallbacks_total",
    "Cycles the fused device-gather path fell back to the host assemble "
    "control by reason (explain = explain plane armed for the chunk, "
    "device-rows = slot-store mirror sync failed/degraded)",
    ("reason",),
)

#: Resident ndarray fields that never cross the host->device boundary
#: beyond what meshing.HOST_ONLY_FIELDS already exempts.  The slot-store
#: arrays share the SolverBatch field names on purpose: the per-cycle
#: GATHERED copies are what dispatch ships, under the same PartitionSpec
#: entries (the spec-coverage vet pass checks ResidentPlane against the
#: same table).
RESIDENT_HOST_ONLY = frozenset()

_ROUTE_DEVICE = tensors.ROUTE_DEVICE


@dataclass
class ResidentPlane:
    """The persistent tensor set (numpy masters, frozen between writes).

    Cluster/placement-side fields are the EXACT arrays dispatch consumes
    (shared verbatim into every cycle's SolverBatch); binding-axis fields
    are the slot store the per-cycle gather reads.  Field names and
    dtypes follow ops/tensors.FIELD_DTYPES — the dtype-contract and
    spec-coverage vet passes check this class like they check
    SolverBatch."""

    # cluster axis
    cluster_valid: np.ndarray
    deleting: np.ndarray
    name_rank: np.ndarray
    pods_allowed: np.ndarray
    has_summary: np.ndarray
    avail_milli: np.ndarray
    has_alloc: np.ndarray
    api_ok: np.ndarray
    # request classes
    req_milli: np.ndarray
    req_is_cpu: np.ndarray
    req_pods: np.ndarray
    est_override: np.ndarray
    # placements
    pl_mask: np.ndarray
    pl_tol_bypass: np.ndarray
    pl_strategy: np.ndarray
    pl_static_w: np.ndarray
    pl_has_cluster_sc: np.ndarray
    pl_sc_min: np.ndarray
    pl_sc_max: np.ndarray
    pl_ignore_avail: np.ndarray
    pl_extra_score: np.ndarray
    region_id: np.ndarray
    pl_has_region_sc: np.ndarray
    pl_region_min: np.ndarray
    pl_region_max: np.ndarray
    # binding-axis slot store (gathered per cycle)
    placement_id: np.ndarray
    gvk_id: np.ndarray
    class_id: np.ndarray
    replicas: np.ndarray
    uid_desc: np.ndarray
    fresh: np.ndarray
    non_workload: np.ndarray
    nw_shortcut: np.ndarray
    route: np.ndarray
    prev_idx: np.ndarray
    prev_val: np.ndarray
    evict_idx: np.ndarray


#: the cluster/placement-side plane fields, in ops/solver._CLUSTER_FIELDS
#: order (the device-slot priming contract), plus the spread-topology
#: fields the dispatch reads off the batch
CLUSTER_SIDE_FIELDS = (
    "cluster_valid", "deleting", "name_rank", "pods_allowed", "has_summary",
    "avail_milli", "has_alloc", "api_ok",
    "req_milli", "req_is_cpu", "req_pods", "est_override",
    "pl_mask", "pl_tol_bypass", "pl_strategy", "pl_static_w",
    "pl_has_cluster_sc", "pl_sc_min", "pl_sc_max", "pl_ignore_avail",
    "pl_extra_score",
)
SHARED_EXTRA_FIELDS = (
    "region_id", "pl_has_region_sc", "pl_region_min", "pl_region_max",
)
BINDING_SLOT_FIELDS = (
    "placement_id", "gvk_id", "class_id", "replicas", "uid_desc",
    "fresh", "non_workload", "nw_shortcut", "route",
)
#: fields whose device mirror can advance by a cluster-LANE scatter
#: (leading axis is C)
ROW_SCATTER_FIELDS = frozenset({
    "cluster_valid", "deleting", "name_rank", "pods_allowed", "has_summary",
    "avail_milli", "has_alloc",
})
#: fields whose device mirror advances by a cluster-COLUMN scatter
#: (trailing axis is C)
COL_SCATTER_FIELDS = frozenset({"est_override", "api_ok"})
#: the binding-axis slot store's device-mirror field set (fused gather
#: path, ops/resident_gather) — MUST equal resident_gather.GATHER_FIELDS
#: (asserted on first sync) and stay covered by meshing.shard_specs /
#: HOST_ONLY_FIELDS (the spec-coverage vet pass checks this tuple)
DEVICE_SLOT_FIELDS = BINDING_SLOT_FIELDS + (
    "prev_idx", "prev_val", "evict_idx")


class RowToken:
    """Identity + validity of one binding's cached encoded row."""

    __slots__ = ("key", "rv")

    def __init__(self, key: str, rv: int) -> None:
        self.key = key
        self.rv = rv


class _Row:
    __slots__ = ("slot", "rv")

    def __init__(self, slot: int, rv: int) -> None:
        self.slot = slot
        self.rv = rv


def _freeze(arr: np.ndarray) -> np.ndarray:
    if isinstance(arr, np.ndarray) and arr.flags.owndata:
        arr.flags.writeable = False
    return arr


class _Txn:
    """Copy-on-write transaction over the frozen plane masters: first
    access of a field copies it writable; commit() freezes the copies,
    swaps them into the plane, and reports which fields changed."""

    def __init__(self, plane: ResidentPlane) -> None:
        self.plane = plane
        self._w: Dict[str, np.ndarray] = {}

    def get(self, field: str) -> np.ndarray:
        arr = self._w.get(field)
        if arr is None:
            arr = np.array(getattr(self.plane, field))  # writable copy
            self._w[field] = arr
        return arr

    def commit(self) -> List[str]:
        for f, arr in self._w.items():
            setattr(self.plane, f, _freeze(arr))
        return list(self._w)


class _DevicePlane:
    """Device mirrors of the cluster-side masters, advanced by the
    ops/resident_update scatter kernels and primed into the solver's
    device-transfer cache so dispatch never re-uploads them."""

    def __init__(self) -> None:
        self.mirrors: Dict[str, object] = {}
        self.np_refs: Dict[str, np.ndarray] = {}
        self.plan_gen: Optional[int] = None
        self.broken = False  # a failed sync disables the mirror path

    def sync(self, plane: ResidentPlane, dirty: Dict[str, object]) -> bool:
        """Advance mirrors to the current masters and prime the solver
        slot.  `dirty` maps field -> lane array for fields whose change
        is a pure cluster-lane/column rewrite (scatter path); any other
        identity change re-places the whole field.  Returns True when the
        slot was primed."""
        if self.broken:
            return False
        try:
            from karmada_tpu.ops import meshing, resident_update
            from karmada_tpu.ops import solver as solver_mod

            plan = meshing.active()
            gen = plan.generation if plan is not None else 0
            fresh = gen != self.plan_gen
            for f in CLUSTER_SIDE_FIELDS:
                master = getattr(plane, f)
                if not fresh and self.np_refs.get(f) is master:
                    continue
                mirror = self.mirrors.get(f)
                lanes = None if fresh else dirty.get(f)
                if mirror is not None and lanes is not None \
                        and getattr(mirror, "shape", None) == master.shape:
                    if f in ROW_SCATTER_FIELDS:
                        lp, rows = resident_update.pad_lanes(
                            lanes, master[lanes])
                        mirror = resident_update.scatter_rows(
                            mirror, lp, rows)
                    elif f in COL_SCATTER_FIELDS:
                        lp, cols = resident_update.pad_lanes_cols(
                            lanes, master[..., lanes])
                        mirror = resident_update.scatter_cols(
                            mirror, lp, cols)
                    else:  # no scatter shape for this field: re-place
                        mirror = solver_mod._put(f, master, plan)  # noqa: SLF001
                else:
                    mirror = solver_mod._put(f, master, plan)  # noqa: SLF001
                self.mirrors[f] = mirror
                self.np_refs[f] = master
            self.plan_gen = gen
            return solver_mod.prime_cluster_slot(
                tuple(self.np_refs[f] for f in CLUSTER_SIDE_FIELDS),
                tuple(self.mirrors[f] for f in CLUSTER_SIDE_FIELDS),
                gen)
        # vet: ignore[exception-hygiene] logged + mirror path disabled (the broken flag IS the record)
        except Exception:  # noqa: BLE001 — mirrors are an optimization:
            # a failed device sync must degrade to plain dispatch-time
            # uploads, never take the scheduler down — but never silently:
            # losing the mirror path re-adds the ~5MB per-dispatch upload
            # for the process lifetime, so the cause must be on record
            import logging

            logging.getLogger(__name__).exception(
                "resident device-mirror sync failed; disabling the mirror "
                "path (dispatch falls back to per-cycle uploads)")
            self.broken = True
            self.mirrors = {}
            self.np_refs = {}
            return False


class _DeviceRows:
    """Device mirrors of the binding-axis slot store (the fused gather
    path, ops/resident_gather).  Masters stay the host source of truth;
    mirrors advance by ROW SCATTERS of exactly the churned slots
    (ops/resident_update.scatter_rows — the [cap]-leading shapes make
    every slot field a row scatter) and full re-places on geometry
    changes (slot-capacity growth, sparse-width growth, rebuild, mesh
    re-plan).  A failed sync degrades the plane to the host assemble
    control — never takes the scheduler down."""

    def __init__(self) -> None:
        self.mirrors: Dict[str, object] = {}
        self.plan_gen: Optional[int] = None
        self.broken = False

    def sync(self, plane: ResidentPlane, dirty) -> bool:
        """Advance the mirrors: `dirty` is None (clean), "full" (re-place
        every field), or an int64 lane array of churned slots (scatter).
        Returns True when the mirrors match the masters."""
        if self.broken:
            return False
        try:
            from karmada_tpu.ops import meshing, resident_gather, \
                resident_update

            assert DEVICE_SLOT_FIELDS == resident_gather.GATHER_FIELDS, \
                "slot-store field set drifted from the gather kernel's"
            plan = meshing.active()
            gen = plan.generation if plan is not None else 0
            full = (isinstance(dirty, str)  # the "full" sentinel
                    or gen != self.plan_gen or not self.mirrors)
            if not full and dirty is None:
                return True
            scattered = 0
            for f in DEVICE_SLOT_FIELDS:
                master = getattr(plane, f)
                mirror = self.mirrors.get(f)
                if (not full and mirror is not None
                        and getattr(mirror, "shape", None) == master.shape):
                    # copy-on-write (no donation): the previous chunk's
                    # async gather may still read this mirror, and
                    # donating a buffer with in-flight consumers stalls
                    # the dispatching thread until they drain
                    lp, rows = resident_update.pad_lanes(
                        dirty, master[dirty])
                    mirror = resident_update.scatter_rows_cow(
                        mirror, lp, rows)
                    scattered = len(dirty)
                else:
                    mirror = resident_gather.place_slot(master, plan)
                self.mirrors[f] = mirror
            if scattered:
                resident_gather.GATHER_SCATTERS.inc(scattered)
            self.plan_gen = gen
            return True
        # vet: ignore[exception-hygiene] logged + fused path disabled (the broken flag IS the record)
        except Exception:  # noqa: BLE001 — the device slot store is an
            # optimization: a failed sync must degrade the fused path to
            # the host assemble control, never take the scheduler down —
            # but never silently: losing it re-adds the per-cycle host
            # assembly + binding-field h2d for the process lifetime
            import logging

            logging.getLogger(__name__).exception(
                "resident device slot-store sync failed; disabling the "
                "fused gather path (cycles fall back to host assembly)")
            self.broken = True
            self.mirrors = {}
            return False


class AuditMismatch(Exception):
    """Raised internally when the parity audit finds divergence."""

    def __init__(self, fields: List[str]) -> None:
        super().__init__(f"resident-vs-full-encode mismatch: {fields}")
        self.fields = fields


class ResidentState:
    """The device-resident state plane for ONE scheduler's device path.

    Driven single-threaded from the scheduler's device cycle; stats are
    lock-guarded for the /debug/resident reader."""

    def __init__(self, estimator: Optional[GeneralEstimator] = None,
                 audit_interval: int = 64, device_plane: bool = True,
                 cycle_log_cap: int = 512, fused: bool = False) -> None:
        self.estimator = estimator or GeneralEstimator()
        self.audit_interval = max(0, int(audit_interval))
        self.device = _DevicePlane() if device_plane else None
        # fused whole-cycle-on-device path (ops/resident_gather): the
        # binding-axis slot store mirrors on device and per-cycle batch
        # rows gather there instead of the host assembling + re-uploading
        # them.  The host assemble stays the behavior-defining control:
        # explain-armed chunks, rebuild cycles, and any mirror-sync
        # failure fall back to it (RESIDENT_GATHER_FALLBACKS).
        self.fused = bool(fused and device_plane)
        self.device_rows = _DeviceRows() if self.fused else None
        # guarded by the cycle thread: None = mirrors clean, "full" =
        # re-place everything, int64 lanes = scatter exactly these slots
        self._rows_dirty: object = "full"

        self.plane: Optional[ResidentPlane] = None
        self.cindex: Optional[tensors.ClusterIndex] = None
        self.clusters: List = []
        self.cluster_rvs: List[int] = []
        self.names: List[str] = []
        self.nC = 0
        self.C = 0
        # vocabularies (append-only between rebuilds)
        self.res_names: List[str] = []
        self.class_keys: List = []
        self.class_reqs: List = []
        self.placements: List = []
        self.pkeys: Dict[str, int] = {}
        self.gvk_keys: List[Tuple[str, str]] = []
        self.gvks: Dict[Tuple[str, str], int] = {}
        self.region_names: List[str] = []
        self.label_axes: Dict[str, tuple] = {}
        self.plugins_gen: Optional[int] = None
        self.enc_cache = tensors.EncoderCache()
        # binding-row slot store
        self.rows: Dict[str, _Row] = {}
        self._free: List[int] = []
        self._next_slot = 0
        self.Kp = 4
        self.Ke = 4
        # explain plane: per-placement static fail-bit rows (+ assembled)
        self._fail_rows: Dict[int, np.ndarray] = {}
        self._fail_plane: Optional[Tuple[tuple, np.ndarray]] = None
        # device-mirror dirtiness accumulated since the last sync
        self._dirty: Dict[str, object] = {}
        self._device_primed = False
        # feasibility-flip lanes of the LAST begin_cycle window: lanes
        # whose `deleting` value or api_ok column actually CHANGED (not
        # merely re-wrote).  Among the delta-applied fields these are the
        # only feasibility inputs (solver feasibility = lanes_ok & pl_mask
        # & (tol_bypass | prev) & (api_ok | prev) & ~evict; the rest of
        # _apply_capacity's fields are capacity-only).  The incremental
        # dirty-set plane (ops/dirty.py) expands these into the rows whose
        # placements cover them.  Owned by the cycle thread.
        self.last_flip_lanes: np.ndarray = np.zeros(0, np.int64)
        # capacity-updated lanes of the LAST begin_cycle window (status
        # writes): the incremental plane retires its carried-consumption
        # ledger per lane on these
        self.last_cap_lanes: np.ndarray = np.zeros(0, np.int64)

        self.generation = 0
        self.cycles = 0
        self._stats_lock = threading.Lock()
        # guarded-by: _stats_lock
        self.fused_cycles = 0
        # guarded-by: _stats_lock
        self.host_cycles = 0
        # guarded-by: _stats_lock
        self.gather_fallbacks: Dict[str, int] = {}
        # guarded-by: _stats_lock — host seconds spent dispatching the
        # fused gather (bench --delta's per-stage host-budget breakdown)
        self.gather_seconds = 0.0
        # guarded-by: _stats_lock
        self.hits = 0
        # guarded-by: _stats_lock
        self.misses = 0
        # guarded-by: _stats_lock
        self.rebuilds: Dict[str, int] = {}
        # guarded-by: _stats_lock
        self.audits_ok = 0
        # guarded-by: _stats_lock
        self.audit_mismatches = 0
        # guarded-by: _stats_lock
        self.last_audit: Optional[dict] = None
        # guarded-by: _stats_lock
        self.last_deltas: dict = {}
        # guarded-by: _stats_lock
        self.cycle_log: deque = deque(maxlen=cycle_log_cap)

    # -- lifecycle -----------------------------------------------------------
    def fork_clusters(self) -> List:
        """A deep-copied fork of the plane's member-cluster view for
        hypothetical (what-if) solves: the masters themselves are frozen
        device arrays shared by reference (copy-on-write by
        construction), and the host-side Cluster objects are the only
        mutable tier — so the fork copies exactly those.  A what-if solve
        may decorate, drain, or delete the copies freely; the live plane
        never observes it.  Returns [] before the first begin_cycle
        (the caller falls back to a store snapshot)."""
        import copy

        return [copy.deepcopy(c) for c in self.clusters]

    def begin_cycle(self, clusters: Sequence,
                    deltas: Optional[CycleDeltas] = None) -> None:
        """Advance the plane to this cycle's cluster snapshot: apply the
        coalesced deltas, or fall back to a full rebuild on any
        structural change.  Must run before the cycle's encode_cycle
        calls, on the same thread.

        The watch-event deltas are a HINT, not the source of truth: the
        store's snapshot (`clusters`, deepcopies) and its watch bus are
        not taken atomically, so an event drained this cycle may describe
        a write the snapshot already contains (no-op apply) or a write
        newer than it (which must NOT be applied ahead of the snapshot).
        The resourceVersion sweep below closes that window — every lane
        whose rv moved against the retained previous snapshot is
        classified old-object-vs-new-object and folded into the delta
        set, so the plane always lands exactly ON this cycle's snapshot
        regardless of event timing."""
        from karmada_tpu.scheduler.plugins import REGISTRY as _PLUGINS

        clusters = list(clusters)
        self.cycles += 1
        self.last_flip_lanes = np.zeros(0, np.int64)
        self.last_cap_lanes = np.zeros(0, np.int64)
        reason = None
        changed: Dict[str, str] = dict(deltas.clusters) if deltas else {}
        if self.plane is None:
            reason = "init"
        elif self.plugins_gen != _PLUGINS.generation:
            reason = "plugin-registry"
        elif deltas is not None and deltas.structural:
            reason = deltas.structural_reason or "cluster-structural"
        elif [c.name for c in clusters] != self.names:
            # defense in depth: membership/order drift the tracker missed
            # (e.g. a store rebuilt underneath us) is structural too
            reason = "membership"
        else:
            # the rv sweep (see docstring): O(nC) int compares per cycle
            for lane, new in enumerate(clusters):
                rv = new.metadata.resource_version
                if rv == self.cluster_rvs[lane]:
                    continue
                cls, why = classify_change(self.clusters[lane], new)
                if cls == STRUCTURAL:
                    reason = why
                    break
                prev = changed.get(new.metadata.name)
                if prev is None or _RANK[cls] > _RANK[prev]:
                    changed[new.metadata.name] = cls
        with obs.TRACER.span(obs.SPAN_RESIDENT_APPLY,
                             clusters=len(clusters),
                             structural=bool(reason),
                             deltas=len(changed)):
            if reason is not None:
                self._reset(clusters, reason)
            else:
                self.clusters = clusters
                self.cluster_rvs = [
                    c.metadata.resource_version for c in clusters]
                # the cycle's mini encodes / audits / big-tier sub-solves
                # must read THIS snapshot's objects, not the adoption
                # cycle's (capacity lives on the cluster objects)
                self.cindex = tensors.ClusterIndex.build(clusters)
                # per-cycle encoder-cache hygiene: placement-key pins hold
                # the previous cycle's binding objects (id-keyed memo) and
                # would grow without bound across a long-running plane
                self.enc_cache.placement_keys = {}
                if changed:
                    self._apply(CycleDeltas(
                        clusters=changed,
                        binding_events=(deltas.binding_events
                                        if deltas else 0)))
            if deltas is not None:
                for key in deltas.bindings_deleted:
                    self.forget(f"{key[0]}/{key[1]}")
        self.plugins_gen = _PLUGINS.generation

    def _reset(self, clusters: List, reason: str) -> None:
        """Drop to the lossless fallback: the next encode_cycle is one
        full encode_batch whose tensors become the new masters."""
        self.plane = None
        self.cindex = tensors.ClusterIndex.build(clusters)
        self.clusters = clusters
        self.cluster_rvs = [c.metadata.resource_version for c in clusters]
        self.names = [c.name for c in clusters]
        self.nC = len(clusters)
        self.C = tensors._next_pow2(max(self.nC, 1), 8)  # noqa: SLF001
        self.res_names = []
        self.class_keys = []
        self.class_reqs = []
        self.placements = []
        self.pkeys = {}
        self.gvk_keys = []
        self.gvks = {}
        self.region_names = []
        self.label_axes = {}
        self.enc_cache = tensors.EncoderCache()
        self.rows = {}
        self._free = []
        self._next_slot = 0
        self.Kp = 4
        self.Ke = 4
        self._fail_rows = {}
        self._fail_plane = None
        self._dirty = {}
        self._device_primed = False
        if self.device is not None:
            # mirrors of the retired generation must not be scatter-based
            self.device.np_refs = {}
        if self.device_rows is not None:
            # retired-generation slot mirrors must never serve a gather
            self.device_rows.mirrors = {}
        self._rows_dirty = "full"
        self.generation += 1
        RESIDENT_GENERATION.set(float(self.generation))
        RESIDENT_REBUILDS.inc(reason=reason)
        with self._stats_lock:
            self.rebuilds[reason] = self.rebuilds.get(reason, 0) + 1

    # -- delta application ---------------------------------------------------
    def _apply(self, deltas: CycleDeltas) -> None:
        cap_lanes: List[int] = []
        api_lanes: List[int] = []
        idx = self.cindex.index
        by_lane: Dict[int, object] = {}
        for i, c in enumerate(self.clusters):
            by_lane[i] = c
        for name, kind in deltas.clusters.items():
            lane = idx.get(name)
            if lane is None:
                continue  # deleted and re-created within the window is
                # membership drift; the names check in begin_cycle owns it
            RESIDENT_DELTAS.inc(kind=kind)
            if kind == CAPACITY:
                cap_lanes.append(lane)
            elif kind == API:
                # an api change rides on a status write: refresh both
                api_lanes.append(lane)
                cap_lanes.append(lane)
        if cap_lanes:
            self.last_cap_lanes = np.asarray(sorted(set(cap_lanes)),
                                             np.int64)
            self._apply_capacity(sorted(set(cap_lanes)), by_lane)
        if api_lanes:
            self._apply_api(sorted(set(api_lanes)), by_lane)
        with self._stats_lock:
            self.last_deltas = {
                "capacity": len(cap_lanes), "api": len(api_lanes),
                "binding_events": deltas.binding_events,
            }

    def _apply_capacity(self, lanes: List[int],
                        by_lane: Dict[int, object]) -> None:
        """Recompute the churned clusters' capacity lanes — the identical
        math encode_batch runs, restricted to `lanes` (bit-exactness is
        the audit's contract)."""
        plane = self.plane
        txn = _Txn(plane)
        deleting = txn.get("deleting")
        has_summary = txn.get("has_summary")
        pods_allowed = txn.get("pods_allowed")
        avail_milli = txn.get("avail_milli")
        has_alloc = txn.get("has_alloc")
        est_override = txn.get("est_override") if self.class_keys else None
        modeling = self.estimator.enable_resource_modeling
        flips: List[int] = []
        for lane in lanes:
            c = by_lane[lane]
            s = c.status.resource_summary
            if bool(deleting[lane]) != bool(c.metadata.deleting):
                # the ONE feasibility input a status write can move
                flips.append(lane)
            deleting[lane] = c.metadata.deleting
            has_summary[lane] = s is not None
            pods_allowed[lane] = tensors._allowed_pods(s) if s is not None \
                else 0  # noqa: SLF001
            avail_milli[lane, :] = 0
            has_alloc[lane, :] = False
            if s is not None:
                for r, name in enumerate(self.res_names):
                    alloc = s.allocatable.get(name)
                    if alloc is None:
                        continue
                    has_alloc[lane, r] = True
                    m = alloc.milli
                    used = s.allocated.get(name)
                    if used is not None:
                        m -= used.milli
                    ing = s.allocating.get(name)
                    if ing is not None:
                        m -= ing.milli
                    avail_milli[lane, r] = m
            if est_override is not None:
                modeled = (modeling and s is not None
                           and s.allocatable_modelings)
                for q, rr in enumerate(self.class_reqs):
                    if modeled and not isinstance(rr, tensors._SetClass):  # noqa: SLF001
                        est_override[q, lane] = \
                            self.estimator._max_for_cluster(c, rr)  # noqa: SLF001
                    else:
                        est_override[q, lane] = -1
        changed = txn.commit()
        lanes_arr = np.asarray(lanes, np.int64)
        for f in changed:
            self._mark_dirty(f, lanes_arr)
        if flips:
            self.last_flip_lanes = np.union1d(
                self.last_flip_lanes, np.asarray(flips, np.int64))
        self._invalidate_enc_cache()

    def _apply_api(self, lanes: List[int],
                   by_lane: Dict[int, object]) -> None:
        if not self.gvk_keys:
            return
        txn = _Txn(self.plane)
        api_ok = txn.get("api_ok")
        flips: List[int] = []
        for lane in lanes:
            c = by_lane[lane]
            old_col = api_ok[:, lane].copy()
            for g, (api_version, kind) in enumerate(self.gvk_keys):
                api_ok[g, lane] = (
                    c.api_enablement(api_version, kind) == serial.API_ENABLED)
            if not np.array_equal(old_col, api_ok[:, lane]):
                flips.append(lane)  # an api_ok flip is a feasibility flip
        if flips:
            self.last_flip_lanes = np.union1d(
                self.last_flip_lanes, np.asarray(flips, np.int64))
        for f in txn.commit():
            self._mark_dirty(f, np.asarray(lanes, np.int64))
        # gvk rows cached in the encoder are stale for these clusters
        self.enc_cache.gvk_rows = {}
        self._invalidate_enc_cache()

    def _invalidate_enc_cache(self) -> None:
        """Status-derived encoder-cache entries went stale: the next miss
        encode must not reuse them.  pods_allowed re-points at the
        (already updated) master so the O(C) rebuild is skipped."""
        c = self.enc_cache
        c.override_rows = {}
        c.assembled = None
        c.assembled_sig = None
        # the cluster-axis bundle caches has_summary/deleting per cycle; a
        # capacity delta can flip has_summary (summary appearing), so the
        # next miss encode rebuilds it
        c.cluster_axis = None
        c.pods_allowed = self.plane.pods_allowed if self.plane is not None \
            else None

    def _mark_dirty(self, field: str, lanes: Optional[np.ndarray]) -> None:
        """Accumulate device-mirror dirtiness: lane-scatterable changes
        merge their lane sets; anything else (or a second non-lane
        change) escalates to a full re-place of that field."""
        if self.device is None:
            return
        if lanes is None or (field not in ROW_SCATTER_FIELDS
                             and field not in COL_SCATTER_FIELDS):
            self._dirty[field] = None
            return
        prev = self._dirty.get(field, _MISSING)
        if prev is _MISSING:
            self._dirty[field] = lanes
        elif prev is None:
            pass  # already a full re-place
        else:
            self._dirty[field] = np.union1d(prev, lanes)
        self._device_primed = False

    # -- the per-cycle encoder -----------------------------------------------
    def encode_cycle(self, items: Sequence,
                     tokens: Optional[Sequence[Optional[RowToken]]] = None,
                     explain: bool = False,
                     audit: Optional[bool] = None) -> tensors.SolverBatch:
        """Encode one cycle chunk: cached rows gather, churned rows
        re-encode through encode_batch and merge.  Returns a SolverBatch
        semantically identical to a fresh full encode (the audit's
        bit-exact contract).  `audit` forces/suppresses the parity audit
        for this call (None = cadence)."""
        n = len(items)
        assert self.cindex is not None, "begin_cycle() before encode_cycle()"
        if self.plane is not None and chaos_mod.armed():
            # chaos seam (resident.mirror:corrupt): flip one value in a
            # resident master and force THIS cycle's parity audit — the
            # corrupted batch must be caught by the audit and replaced by
            # the fresh encode before the solve reads it (auditable
            # rebuild, never a wrong placement)
            f = chaos_mod.fire(chaos_mod.SITE_RESIDENT_MIRROR,
                               generation=self.generation)
            if f is not None and f.mode == "corrupt":
                self._chaos_corrupt()
                audit = True
        if self.plane is None:
            # lossless fallback path: ONE full encode, adopted as masters
            batch = tensors.encode_batch(items, self.cindex, self.estimator,
                                         cache=self.enc_cache,
                                         explain=explain)
            self._adopt(batch, items, tokens)
            RESIDENT_LOOKUPS.inc(n, result="miss")
            with self._stats_lock:
                self.misses += n
            self._log_cycle(n, hits=0, misses=n, rebuilt=True)
            self._sync_device()
            return batch

        slots = np.zeros(n, np.int64)
        miss_pos: List[int] = []
        hits = 0
        for i in range(n):
            tok = tokens[i] if tokens is not None else None
            if tok is not None:
                row = self.rows.get(tok.key)
                if row is not None and row.rv == tok.rv:
                    slots[i] = row.slot
                    hits += 1
                    continue
            miss_pos.append(i)
        with obs.TRACER.span(obs.SPAN_RESIDENT_ENCODE, items=n,
                             hits=hits, misses=len(miss_pos),
                             fused=self.fused):
            if miss_pos:
                mini = tensors.encode_batch(
                    [items[i] for i in miss_pos], self.cindex,
                    self.estimator, cache=self.enc_cache)
                self._merge(mini, miss_pos, tokens, slots)
            batch = None
            if self.fused:
                # fused whole-cycle-on-device path: the churned slots
                # just scattered into the device store; the batch rows
                # now GATHER there.  Explain-armed chunks keep the host
                # control (the explain planes decode host-side per row).
                if explain:
                    RESIDENT_GATHER_FALLBACKS.inc(reason="explain")
                    with self._stats_lock:
                        self.gather_fallbacks["explain"] = \
                            self.gather_fallbacks.get("explain", 0) + 1
                else:
                    batch = self._assemble_fused(slots, n)
                    if batch is None:
                        RESIDENT_GATHER_FALLBACKS.inc(reason="device-rows")
                        with self._stats_lock:
                            self.gather_fallbacks["device-rows"] = \
                                self.gather_fallbacks.get("device-rows",
                                                          0) + 1
            if batch is None:
                batch = self._assemble(items, slots, n, explain)
                with self._stats_lock:
                    self.host_cycles += 1
            else:
                with self._stats_lock:
                    self.fused_cycles += 1
        RESIDENT_LOOKUPS.inc(hits, result="hit")
        RESIDENT_LOOKUPS.inc(len(miss_pos), result="miss")
        with self._stats_lock:
            self.hits += hits
            self.misses += len(miss_pos)
        self._log_cycle(n, hits=hits, misses=len(miss_pos), rebuilt=False)
        run_audit = (audit if audit is not None
                     else (self.audit_interval > 0
                           and self.cycles % self.audit_interval == 0))
        if run_audit:
            fresh = self.audit(items, batch, tokens, explain=explain)
            if fresh is not None:
                return fresh
        self._sync_device()
        return batch

    def _chaos_corrupt(self) -> None:
        """Bit-flip one LIVE lane of a cluster-side master (the fault a
        bad DMA / cosmic ray / buggy scatter kernel would produce),
        through the same copy-on-write transaction real updates use, and
        mark the mirror dirty so the corruption propagates exactly as
        far as a real one would.  pods_allowed on a valid lane: a value
        every solve reads and the parity audit compares unconditionally
        — corruption in padded/retired vocabulary would be (correctly)
        invisible to both."""
        txn = _Txn(self.plane)
        arr = txn.get("pods_allowed")
        arr[max(self.nC - 1, 0) // 2] += 1
        txn.commit()
        self._mark_dirty("pods_allowed", None)

    def forget(self, key: str) -> None:
        """Drop one binding's cached row (binding deleted)."""
        row = self.rows.pop(key, None)
        if row is not None:
            self._free.append(row.slot)
        RESIDENT_ROWS.set(float(len(self.rows)))

    # -- adopt / merge / assemble --------------------------------------------
    def _adopt(self, batch: tensors.SolverBatch, items: Sequence,
               tokens: Optional[Sequence[Optional[RowToken]]]) -> None:
        """Take a full encode's tensors as the new resident masters."""
        n = batch.n_bindings
        self.res_names = list(batch.res_names)
        self.class_keys = list(batch.class_keys)
        self.class_reqs = list(batch.class_reqs or [])
        self.placements = list(batch.placements or [])
        self.pkeys = {tensors._placement_key(p): i  # noqa: SLF001
                      for i, p in enumerate(self.placements)}
        self.gvk_keys = list(batch.gvk_keys or [])
        self.gvks = {g: i for i, g in enumerate(self.gvk_keys)}
        self.region_names = list(batch.region_names or [])
        self.label_axes = dict(batch.label_axes or {})
        self.Kp = batch.prev_idx.shape[1]
        self.Ke = batch.evict_idx.shape[1]
        cap = tensors._next_pow2(max(n, 64), 64)  # noqa: SLF001
        placement_id = np.zeros(cap, np.int32)
        gvk_id = np.zeros(cap, np.int32)
        class_id = np.full(cap, -1, np.int32)
        replicas = np.zeros(cap, np.int64)
        uid_desc = np.zeros(cap, bool)
        fresh = np.zeros(cap, bool)
        non_workload = np.zeros(cap, bool)
        nw_shortcut = np.zeros(cap, bool)
        route = np.zeros(cap, np.int32)
        prev_idx = np.full((cap, self.Kp), -1, np.int32)
        prev_val = np.zeros((cap, self.Kp), np.int32)
        evict_idx = np.full((cap, self.Ke), -1, np.int32)
        placement_id[:n] = batch.placement_id[:n]
        gvk_id[:n] = batch.gvk_id[:n]
        class_id[:n] = batch.class_id[:n]
        replicas[:n] = batch.replicas[:n]
        uid_desc[:n] = batch.uid_desc[:n]
        fresh[:n] = batch.fresh[:n]
        non_workload[:n] = batch.non_workload[:n]
        nw_shortcut[:n] = batch.nw_shortcut[:n]
        route[:n] = batch.route[:n]
        prev_idx[:n] = batch.prev_idx[:n]
        prev_val[:n] = batch.prev_val[:n]
        evict_idx[:n] = batch.evict_idx[:n]
        self.plane = ResidentPlane(
            cluster_valid=batch.cluster_valid, deleting=batch.deleting,
            name_rank=batch.name_rank, pods_allowed=batch.pods_allowed,
            has_summary=batch.has_summary, avail_milli=batch.avail_milli,
            has_alloc=batch.has_alloc, api_ok=batch.api_ok,
            req_milli=batch.req_milli, req_is_cpu=batch.req_is_cpu,
            req_pods=batch.req_pods, est_override=batch.est_override,
            pl_mask=batch.pl_mask, pl_tol_bypass=batch.pl_tol_bypass,
            pl_strategy=batch.pl_strategy, pl_static_w=batch.pl_static_w,
            pl_has_cluster_sc=batch.pl_has_cluster_sc,
            pl_sc_min=batch.pl_sc_min, pl_sc_max=batch.pl_sc_max,
            pl_ignore_avail=batch.pl_ignore_avail,
            pl_extra_score=batch.pl_extra_score,
            region_id=batch.region_id,
            pl_has_region_sc=batch.pl_has_region_sc,
            pl_region_min=batch.pl_region_min,
            pl_region_max=batch.pl_region_max,
            placement_id=placement_id, gvk_id=gvk_id, class_id=class_id,
            replicas=replicas, uid_desc=uid_desc, fresh=fresh,
            non_workload=non_workload, nw_shortcut=nw_shortcut, route=route,
            prev_idx=prev_idx, prev_val=prev_val, evict_idx=evict_idx,
        )
        for f in (CLUSTER_SIDE_FIELDS + SHARED_EXTRA_FIELDS):
            _freeze(getattr(self.plane, f))
        self.rows = {}
        self._free = []
        self._next_slot = n
        if tokens is not None:
            for i in range(n):
                tok = tokens[i]
                if tok is not None:
                    self.rows[tok.key] = _Row(i, tok.rv)
        # slots of untokened rows are reusable immediately (their data was
        # gathered into the returned batch already — it IS the batch)
        if tokens is not None:
            self._free.extend(i for i in range(n) if tokens[i] is None)
        else:
            self._free.extend(range(n))
        self._dirty = {}  # fresh masters: full re-place on next sync
        if self.device is not None:
            self.device.np_refs = {}
        self._rows_dirty = "full"  # fresh slot masters likewise
        self._update_vocab_gauges()

    def _alloc_slots(self, k: int) -> np.ndarray:
        out = np.empty(k, np.int64)
        j = 0
        while j < k and self._free:
            out[j] = self._free.pop()
            j += 1
        if j < k:
            need = self._next_slot + (k - j)
            cap = self.plane.placement_id.shape[0]
            if need > cap:
                self._grow_rows(need)
            out[j:] = np.arange(self._next_slot, need)
            self._next_slot = need
        return out

    def _grow_rows(self, need: int) -> None:
        cap = tensors._next_pow2(need, 64)  # noqa: SLF001
        self._rows_dirty = "full"  # slot geometry changes: re-place
        p = self.plane
        for f in DEVICE_SLOT_FIELDS:
            old = getattr(p, f)
            shape = (cap,) + old.shape[1:]
            if f in ("prev_idx", "evict_idx"):
                new = np.full(shape, -1, old.dtype)
            else:
                new = np.zeros(shape, old.dtype)
            new[:old.shape[0]] = old
            setattr(p, f, new)

    def _widen_sparse(self, field: str, width: int) -> None:
        self._rows_dirty = "full"  # sparse width changes: re-place
        p = self.plane
        old = getattr(p, field)
        fill = -1 if field in ("prev_idx", "evict_idx") else 0
        new = np.full((old.shape[0], width), fill, old.dtype)
        new[:, :old.shape[1]] = old
        setattr(p, field, new)

    def _merge(self, mini: tensors.SolverBatch, miss_pos: List[int],
               tokens: Optional[Sequence[Optional[RowToken]]],
               slots: np.ndarray) -> None:
        """Fold a miss-subset encode into the resident state: vocabulary
        entries append (translating new rows/columns out of the mini
        batch), binding rows land in slots with remapped ids."""
        nm = mini.n_bindings
        # -- vocabulary translation maps -------------------------------------
        rmap = np.zeros(max(len(mini.res_names), 1), np.int64)
        for rm, name in enumerate(mini.res_names):
            r = self._res_index(name, mini, rm)
            rmap[rm] = r
        pmap = np.zeros(max(len(mini.placements or []), 1), np.int32)
        for pm, pl in enumerate(mini.placements or []):
            pmap[pm] = self._placement_index(pl, mini, pm)
        qmap = np.zeros(max(len(mini.class_keys), 1), np.int32)
        for qm, key in enumerate(mini.class_keys):
            qmap[qm] = self._class_index(key, mini, qm, rmap)
        gmap = np.zeros(max(len(mini.gvk_keys or []), 1), np.int32)
        for gm, gk in enumerate(mini.gvk_keys or []):
            gmap[gm] = self._gvk_index(gk, mini, gm)
        for lk, axis in (mini.label_axes or {}).items():
            self.label_axes.setdefault(lk, axis)
        # -- binding rows ----------------------------------------------------
        if mini.prev_idx.shape[1] > self.Kp:
            self.Kp = mini.prev_idx.shape[1]
            self._widen_sparse("prev_idx", self.Kp)
            self._widen_sparse("prev_val", self.Kp)
        if mini.evict_idx.shape[1] > self.Ke:
            self.Ke = mini.evict_idx.shape[1]
            self._widen_sparse("evict_idx", self.Ke)
        # reuse the slot of a key whose row went stale; allocate otherwise
        mslots = np.empty(nm, np.int64)
        fresh_needed: List[int] = []
        for j, i in enumerate(miss_pos):
            tok = tokens[i] if tokens is not None else None
            row = self.rows.get(tok.key) if tok is not None else None
            if row is not None:
                mslots[j] = row.slot
                row.rv = tok.rv
            else:
                fresh_needed.append(j)
        if fresh_needed:
            newly = self._alloc_slots(len(fresh_needed))
            for k, j in enumerate(fresh_needed):
                mslots[j] = newly[k]
                tok = tokens[miss_pos[j]] if tokens is not None else None
                if tok is not None:
                    self.rows[tok.key] = _Row(int(newly[k]), tok.rv)
                else:
                    self._free.append(int(newly[k]))
        p = self.plane
        cid = mini.class_id[:nm]
        p.placement_id[mslots] = pmap[mini.placement_id[:nm]]
        p.gvk_id[mslots] = gmap[mini.gvk_id[:nm]]
        p.class_id[mslots] = np.where(
            cid >= 0, qmap[np.maximum(cid, 0)], -1).astype(np.int32)
        p.replicas[mslots] = mini.replicas[:nm]
        p.uid_desc[mslots] = mini.uid_desc[:nm]
        p.fresh[mslots] = mini.fresh[:nm]
        p.non_workload[mslots] = mini.non_workload[:nm]
        p.nw_shortcut[mslots] = mini.nw_shortcut[:nm]
        p.route[mslots] = mini.route[:nm]
        kpm = mini.prev_idx.shape[1]
        p.prev_idx[mslots, :] = -1
        p.prev_val[mslots, :] = 0
        p.prev_idx[mslots[:, None], np.arange(kpm)[None, :]] = \
            mini.prev_idx[:nm]
        p.prev_val[mslots[:, None], np.arange(kpm)[None, :]] = \
            mini.prev_val[:nm]
        kem = mini.evict_idx.shape[1]
        p.evict_idx[mslots, :] = -1
        p.evict_idx[mslots[:, None], np.arange(kem)[None, :]] = \
            mini.evict_idx[:nm]
        slots[miss_pos] = mslots
        self._mark_rows_dirty(mslots)
        RESIDENT_ROWS.set(float(len(self.rows)))
        self._update_vocab_gauges()

    def _mark_rows_dirty(self, slots: np.ndarray) -> None:
        """Accumulate device slot-store dirtiness (fused gather path):
        churned slot sets union; a pending full re-place absorbs them."""
        if self.device_rows is None:
            return
        if isinstance(self._rows_dirty, str):
            return  # full re-place already pending
        lanes = np.unique(np.asarray(slots, np.int64))
        self._rows_dirty = (lanes if self._rows_dirty is None
                            else np.union1d(self._rows_dirty, lanes))

    def _res_index(self, name: str, mini: tensors.SolverBatch,
                   rm: int) -> int:
        try:
            return self.res_names.index(name)
        except ValueError:
            pass
        r = len(self.res_names)
        p = self.plane
        R = p.avail_milli.shape[1]
        txn = _Txn(p)
        if r >= R:
            R2 = R * 2
            for f, fill in (("avail_milli", 0), ("has_alloc", False),
                            ("req_milli", 0), ("req_is_cpu", False)):
                old = getattr(p, f)
                new = np.full((old.shape[0], R2) if old.ndim == 2 else (R2,),
                              fill, old.dtype)
                if old.ndim == 2:
                    new[:, :R] = old
                else:
                    new[:R] = old
                txn._w[f] = new  # noqa: SLF001 — txn adopts the grown copy
        avail = txn.get("avail_milli")
        alloc = txn.get("has_alloc")
        is_cpu = txn.get("req_is_cpu")
        avail[:, r] = mini.avail_milli[:, rm]
        alloc[:, r] = mini.has_alloc[:, rm]
        is_cpu[r] = mini.req_is_cpu[rm]
        for f in txn.commit():
            self._mark_dirty(f, None)
        self.res_names.append(name)
        return r

    def _class_index(self, key, mini: tensors.SolverBatch, qm: int,
                     rmap: np.ndarray) -> int:
        for q, k in enumerate(self.class_keys):
            if k == key:
                return q
        q = len(self.class_keys)
        p = self.plane
        Q = p.req_milli.shape[0]
        txn = _Txn(p)
        if q >= Q:
            Q2 = Q * 2
            for f, fill in (("req_milli", 0), ("req_pods", 1),
                            ("est_override", -1)):
                old = getattr(p, f)
                new = np.full((Q2,) + old.shape[1:], fill, old.dtype)
                new[:Q] = old
                txn._w[f] = new  # noqa: SLF001
        req_milli = txn.get("req_milli")
        req_pods = txn.get("req_pods")
        est_override = txn.get("est_override")
        row = np.zeros(req_milli.shape[1], np.int64)
        nR = len(mini.res_names)
        row[rmap[:nR]] = mini.req_milli[qm, :nR]
        req_milli[q] = row
        req_pods[q] = mini.req_pods[qm]
        est_override[q] = mini.est_override[qm]
        for f in txn.commit():
            self._mark_dirty(f, None)
        self.class_keys.append(key)
        reqs = mini.class_reqs or []
        self.class_reqs.append(reqs[qm] if qm < len(reqs) else None)
        return q

    def _placement_index(self, pl, mini: tensors.SolverBatch,
                         pm: int) -> int:
        key = tensors._placement_key(pl)  # noqa: SLF001
        pid = self.pkeys.get(key)
        if pid is not None:
            return pid
        pid = len(self.placements)
        p = self.plane
        P = p.pl_strategy.shape[0]
        txn = _Txn(p)
        if pid >= P:
            P2 = P * 2
            for f in ("pl_mask", "pl_tol_bypass", "pl_strategy",
                      "pl_static_w", "pl_has_cluster_sc", "pl_sc_min",
                      "pl_sc_max", "pl_ignore_avail", "pl_extra_score",
                      "pl_has_region_sc", "pl_region_min", "pl_region_max"):
                old = getattr(p, f)
                new = np.zeros((P2,) + old.shape[1:], old.dtype)
                new[:P] = old
                txn._w[f] = new  # noqa: SLF001
        for f in ("pl_mask", "pl_tol_bypass", "pl_strategy", "pl_static_w",
                  "pl_has_cluster_sc", "pl_sc_min", "pl_sc_max",
                  "pl_ignore_avail", "pl_extra_score", "pl_has_region_sc",
                  "pl_region_min", "pl_region_max"):
            txn.get(f)[pid] = getattr(mini, f)[pm]
        for f in txn.commit():
            self._mark_dirty(f, None)
        self.placements.append(pl)
        self.pkeys[key] = pid
        self._fail_plane = None  # the [P, C] explain plane grew
        return pid

    def _gvk_index(self, gk: Tuple[str, str], mini: tensors.SolverBatch,
                   gm: int) -> int:
        g = self.gvks.get(gk)
        if g is not None:
            return g
        g = len(self.gvk_keys)
        p = self.plane
        G = p.api_ok.shape[0]
        txn = _Txn(p)
        if g >= G:
            G2 = G * 2
            old = p.api_ok
            new = np.zeros((G2,) + old.shape[1:], old.dtype)
            new[:G] = old
            txn._w["api_ok"] = new  # noqa: SLF001
        txn.get("api_ok")[g] = mini.api_ok[gm]
        for f in txn.commit():
            self._mark_dirty(f, None)
        self.gvk_keys.append(gk)
        self.gvks[gk] = g
        return g

    def _assemble(self, items: Sequence, slots: np.ndarray, n: int,
                  explain: bool) -> tensors.SolverBatch:
        p = self.plane
        B = tensors._next_pow2(max(n, 1), 8)  # noqa: SLF001
        placement_id = np.zeros(B, np.int32)
        gvk_id = np.zeros(B, np.int32)
        class_id = np.full(B, -1, np.int32)
        replicas = np.zeros(B, np.int64)
        uid_desc = np.zeros(B, bool)
        fresh = np.zeros(B, bool)
        non_workload = np.zeros(B, bool)
        nw_shortcut = np.zeros(B, bool)
        b_valid = np.zeros(B, bool)
        prev_idx = np.full((B, self.Kp), -1, np.int32)
        prev_val = np.zeros((B, self.Kp), np.int32)
        evict_idx = np.full((B, self.Ke), -1, np.int32)
        sl = slots[:n]
        placement_id[:n] = p.placement_id[sl]
        gvk_id[:n] = p.gvk_id[sl]
        class_id[:n] = p.class_id[sl]
        replicas[:n] = p.replicas[sl]
        uid_desc[:n] = p.uid_desc[sl]
        fresh[:n] = p.fresh[sl]
        non_workload[:n] = p.non_workload[sl]
        nw_shortcut[:n] = p.nw_shortcut[sl]
        route = np.ascontiguousarray(p.route[sl], np.int32)
        b_valid[:n] = route == _ROUTE_DEVICE
        prev_idx[:n] = p.prev_idx[sl]
        prev_val[:n] = p.prev_val[sl]
        evict_idx[:n] = p.evict_idx[sl]
        shared = {f: getattr(p, f)
                  for f in CLUSTER_SIDE_FIELDS + SHARED_EXTRA_FIELDS}
        fail_plane = self._ensure_fail_plane() if explain else None
        batch = tensors._build_solver_batch(  # noqa: SLF001
            shared, B, self.C, n, self.nC, b_valid, placement_id, gvk_id,
            class_id, replicas, uid_desc, fresh, non_workload, nw_shortcut,
            prev_idx, prev_val, evict_idx, route, self.cindex,
            list(self.region_names), list(self.res_names),
            list(self.class_keys), dict(self.label_axes), explain,
            fail_plane)
        batch.placements = list(self.placements)
        batch.gvk_keys = list(self.gvk_keys)
        batch.class_reqs = list(self.class_reqs)
        return batch

    def _assemble_fused(self, slots: np.ndarray,
                        n: int) -> Optional[tensors.SolverBatch]:
        """The fused assemble: binding-axis fields gather from the device
        slot store (ops/resident_gather) and ride into the dispatch as
        live device arrays — the only per-cycle h2d is the [B] slot
        vector.  Host keeps exactly what the host path needs: `route`
        (routing/decode) and the donation-safety nnz bound, both O(n)
        gathers off the masters.  Returns None when the device mirrors
        cannot sync (caller falls back to the host control)."""
        from karmada_tpu.ops import meshing, resident_gather

        p = self.plane
        if not self.device_rows.sync(p, self._rows_dirty):
            return None
        self._rows_dirty = None
        sl = slots[:n]
        B = tensors._next_pow2(max(n, 1), 8)  # noqa: SLF001
        slots_b = np.full(B, -1, np.int64)
        slots_b[:n] = sl
        plan = meshing.active()
        t0 = time.perf_counter()
        out = resident_gather.dispatch_gather(
            slots_b, self.device_rows.mirrors, plan)
        with self._stats_lock:
            # dispatch cost only — the gather executes async on device
            self.gather_seconds += time.perf_counter() - t0
        resident_gather.GATHER_ROWS.inc(n)
        (b_valid, placement_id, gvk_id, class_id, replicas, uid_desc,
         fresh, non_workload, nw_shortcut, prev_idx, prev_val,
         evict_idx) = out
        route = np.ascontiguousarray(p.route[sl], np.int32)
        # host companions: decode reads non_workload per binding, and
        # converting the device plane mid-pipeline can block behind the
        # next chunk's in-flight solve on the runtime's transfer path
        nw_host = np.ascontiguousarray(p.non_workload[sl])
        # donation-safety bound (solver._nnz_bound semantics), computed
        # from the host masters so the solver never reads device
        # operands back: wide rows (Duplicated / non-workload) count the
        # full cluster axis, the rest their own replica target + the
        # sparse prev width
        validh = route == _ROUTE_DEVICE
        strat = p.pl_strategy[p.placement_id[sl]]
        wide = validh & ((strat == tensors.STRAT_DUPLICATED)
                         | nw_host)
        per_row = np.minimum(p.replicas[sl], self.C) + self.Kp
        bound = int(np.sum(wide)) * self.C + int(np.sum(per_row[validh
                                                                & ~wide]))
        shared = {f: getattr(p, f)
                  for f in CLUSTER_SIDE_FIELDS + SHARED_EXTRA_FIELDS}
        batch = tensors._build_solver_batch(  # noqa: SLF001
            shared, B, self.C, n, self.nC, b_valid, placement_id, gvk_id,
            class_id, replicas, uid_desc, fresh, non_workload, nw_shortcut,
            prev_idx, prev_val, evict_idx, route, self.cindex,
            list(self.region_names), list(self.res_names),
            list(self.class_keys), dict(self.label_axes), False, None)
        batch.placements = list(self.placements)
        batch.gvk_keys = list(self.gvk_keys)
        batch.class_reqs = list(self.class_reqs)
        batch.fused = True
        batch.nnz_bound_hint = bound
        batch.non_workload_host = nw_host
        # fused-source handle (ops/shortlist under --resident-fused): the
        # frozen host masters + this chunk's slot vector + the live slot
        # mirrors let the shortlist read binding fields LAZILY host-side
        # (tier-1 profiles are host math) and sub-gather the binding rows
        # straight into its sub-vocabulary on device — all without the
        # dense path's per-chunk h2d.  Masters are copy-on-write frozen,
        # so holding references across the chunk's lifetime is safe; the
        # mirrors dict is current until the NEXT encode_cycle's sync, and
        # the shortlist consumes it at shrink time (same thread, before
        # that sync).
        batch.fused_src = {"plane": p, "slots": sl, "slots_b": slots_b,
                           "mirrors": self.device_rows.mirrors,
                           "plan": plan}
        return batch

    def _ensure_fail_plane(self) -> np.ndarray:
        """The [P, C] explain fail-bit plane over the resident placement
        vocabulary (obs/decisions layout), cached until placements or the
        cluster plane change structurally."""
        P = self.plane.pl_strategy.shape[0]
        sig = (self.generation, len(self.placements), P)
        if self._fail_plane is not None and self._fail_plane[0] == sig:
            return self._fail_plane[1]
        from karmada_tpu.scheduler.plugins import REGISTRY as _PLUGINS

        plug_filters = _PLUGINS.enabled_filters()
        dummy = ResourceBindingStatus()
        plane = np.zeros((P, self.C), np.int32)
        for pid, pl in enumerate(self.placements):
            fb = self._fail_rows.get(pid)
            if fb is None:
                fb = tensors._fail_row(pl, self.clusters, self.C,  # noqa: SLF001
                                       plug_filters, dummy)
                self._fail_rows[pid] = fb
            plane[pid] = fb
        _freeze(plane)
        self._fail_plane = (sig, plane)
        return plane

    # -- audit ---------------------------------------------------------------
    def audit(self, items: Sequence, batch: tensors.SolverBatch,
              tokens: Optional[Sequence[Optional[RowToken]]] = None,
              explain: bool = False) -> Optional[tensors.SolverBatch]:
        """Re-encode `items` from scratch and compare bit-exact against
        the resident batch.  On mismatch: count it, force a rebuild, and
        return the fresh batch (which the caller must serve — `explain`
        must match the audited batch's arming so the served batch keeps
        its explain planes); on parity returns None."""
        with obs.TRACER.span(obs.SPAN_RESIDENT_AUDIT, items=len(items)):
            fresh = tensors.encode_batch(items, self.cindex, self.estimator,
                                         explain=explain)
            mismatches = compare_batches(batch, fresh)
        outcome = "mismatch" if mismatches else "ok"
        RESIDENT_AUDITS.inc(outcome=outcome)
        with self._stats_lock:
            if mismatches:
                self.audit_mismatches += 1
            else:
                self.audits_ok += 1
            self.last_audit = {"cycle": self.cycles, "outcome": outcome,
                               "fields": mismatches[:8],
                               "ts": time.time()}
        if not mismatches:
            return None
        # incident trigger (obs/incidents): divergence adoption is a
        # forensic moment — capture the flight ring + plane state before
        # the rebuild papers over it.  Lazy import: the resident plane
        # must stay importable without the obs package loaded.
        from karmada_tpu.obs import incidents as obs_incidents

        obs_incidents.trigger(
            obs_incidents.TRIGGER_AUDIT_DIVERGENCE,
            f"resident audit divergence adopted: {len(mismatches)} "
            "diverged field(s); plane rebuilt from scratch",
            detail={"plane": "resident", "fields": mismatches[:8],
                    "cycle": self.cycles, "items": len(items)})
        self._reset(self.clusters, "audit-mismatch")
        # adopt the fresh encode so the plane is resident again next cycle
        self._adopt(fresh, items, tokens)
        self._log_cycle(len(items), hits=0, misses=len(items), rebuilt=True)
        self._sync_device()
        return fresh

    # -- device plane --------------------------------------------------------
    def _sync_device(self) -> None:
        if self.device is None or self.plane is None:
            return
        if self._device_primed and not self._dirty:
            return
        primed = self.device.sync(self.plane, self._dirty)
        self._dirty = {}
        self._device_primed = primed

    # -- introspection -------------------------------------------------------
    def _log_cycle(self, n: int, hits: int, misses: int,
                   rebuilt: bool) -> None:
        with self._stats_lock:
            self.cycle_log.append({"cycle": self.cycles, "items": n,
                                   "hits": hits, "misses": misses,
                                   "rebuilt": rebuilt})

    def _update_vocab_gauges(self) -> None:
        RESIDENT_VOCAB.set(float(self.nC), axis="clusters")
        RESIDENT_VOCAB.set(float(len(self.placements)), axis="placements")
        RESIDENT_VOCAB.set(float(len(self.class_keys)), axis="classes")
        RESIDENT_VOCAB.set(float(len(self.res_names)), axis="resources")
        RESIDENT_VOCAB.set(float(len(self.gvk_keys)), axis="gvks")
        RESIDENT_ROWS.set(float(len(self.rows)))

    def stats(self) -> dict:
        """Stats payload for /debug/resident, /debug/state and the SOAK
        report.  The counter fields are read under their lock; the plane
        fields (generation, vocab sizes, rows) belong to the scheduler's
        cycle thread, so a poll racing a rebuild may pair a fresh
        generation with the retiring vocabulary for one read —
        diagnostics-only, never consulted by the solve path."""
        with self._stats_lock:
            hits, misses = self.hits, self.misses
            total = hits + misses
            out = {
                "enabled": True,
                "generation": self.generation,
                "resident": self.plane is not None,
                "cycles": self.cycles,
                "vocab": {
                    "clusters": self.nC,
                    "placements": len(self.placements),
                    "classes": len(self.class_keys),
                    "resources": len(self.res_names),
                    "gvks": len(self.gvk_keys),
                    "cluster_lanes": self.C,
                },
                "rows_cached": len(self.rows),
                "row_hits": hits,
                "row_misses": misses,
                "hit_rate": round(hits / total, 4) if total else None,
                "rebuilds": dict(self.rebuilds),
                "audits": {"ok": self.audits_ok,
                           "mismatch": self.audit_mismatches},
                "last_audit": self.last_audit,
                "last_deltas": self.last_deltas,
                "device_plane": (self.device is not None
                                 and not self.device.broken),
                "device_primed": self._device_primed,
                "fused": {
                    "armed": self.fused,
                    "available": (self.device_rows is not None
                                  and not self.device_rows.broken),
                    "cycles": self.fused_cycles,
                    "host_cycles": self.host_cycles,
                    "fallbacks": dict(self.gather_fallbacks),
                    "gather_s": round(self.gather_seconds, 6),
                    "rows_synced": (self.device_rows is not None
                                    and not self.device_rows.broken
                                    and self._rows_dirty is None),
                },
            }
        return out

    def recent_cycles(self, limit: int = 64) -> List[dict]:
        with self._stats_lock:
            log = list(self.cycle_log)
        return log[-limit:]


class _Missing:
    pass


_MISSING = _Missing()


# -- bit-exact comparison -----------------------------------------------------
def compare_batches(resident: tensors.SolverBatch,
                    fresh: tensors.SolverBatch) -> List[str]:
    """Vocabulary-mapped bit-exact comparison of a resident batch against
    a fresh full encode of the same (items, clusters).

    The resident axes may be larger (retired vocabulary entries, padded
    growth); every value the solve can READ must match: cluster lanes,
    per-key placement/class/gvk/resource rows, and per-binding fields
    with ids mapped through the key spaces.  Returns the mismatching
    field names ([] = parity)."""
    errs: List[str] = []

    def chk(name: str, a, b) -> None:
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            errs.append(name)

    if (resident.n_clusters, resident.C) != (fresh.n_clusters, fresh.C):
        return ["cluster-axis-shape"]
    if resident.n_bindings != fresh.n_bindings:
        return ["binding-count"]
    nB = fresh.n_bindings
    for f in ("cluster_valid", "deleting", "name_rank", "pods_allowed",
              "has_summary", "region_id"):
        chk(f, getattr(resident, f), getattr(fresh, f))
    chk("region_names", np.asarray(resident.region_names or [], object),
        np.asarray(fresh.region_names or [], object))
    # resources (by name)
    try:
        rmap = [resident.res_names.index(nm) for nm in fresh.res_names]
    except ValueError:
        return errs + ["resource-vocab"]
    for rm, r in enumerate(rmap):
        chk(f"avail_milli[{fresh.res_names[rm]}]",
            resident.avail_milli[:, r], fresh.avail_milli[:, rm])
        chk(f"has_alloc[{fresh.res_names[rm]}]",
            resident.has_alloc[:, r], fresh.has_alloc[:, rm])
        chk(f"req_is_cpu[{fresh.res_names[rm]}]",
            resident.req_is_cpu[r], fresh.req_is_cpu[rm])
    # classes (by canonical key)
    qmap: List[int] = []
    for key in fresh.class_keys:
        try:
            qmap.append(resident.class_keys.index(key))
        except ValueError:
            return errs + ["class-vocab"]
    for qm, q in enumerate(qmap):
        chk(f"req_milli[q{qm}]",
            resident.req_milli[q][rmap], fresh.req_milli[qm,
                                                         :len(rmap)])
        chk(f"req_pods[q{qm}]", resident.req_pods[q], fresh.req_pods[qm])
        chk(f"est_override[q{qm}]",
            resident.est_override[q], fresh.est_override[qm])
    # placements (by key)
    pmap: List[int] = []
    res_pk = {tensors._placement_key(p): i  # noqa: SLF001
              for i, p in enumerate(resident.placements or [])}
    for pl in (fresh.placements or []):
        pid = res_pk.get(tensors._placement_key(pl))  # noqa: SLF001
        if pid is None:
            return errs + ["placement-vocab"]
        pmap.append(pid)
    for pm, pid in enumerate(pmap):
        for f in ("pl_mask", "pl_tol_bypass", "pl_strategy", "pl_static_w",
                  "pl_has_cluster_sc", "pl_sc_min", "pl_sc_max",
                  "pl_ignore_avail", "pl_extra_score", "pl_has_region_sc",
                  "pl_region_min", "pl_region_max"):
            chk(f"{f}[p{pm}]", getattr(resident, f)[pid],
                getattr(fresh, f)[pm])
    # gvks (by key)
    gmap: List[int] = []
    res_gk = {g: i for i, g in enumerate(resident.gvk_keys or [])}
    for gk in (fresh.gvk_keys or []):
        g = res_gk.get(gk)
        if g is None:
            return errs + ["gvk-vocab"]
        gmap.append(g)
    for gm, g in enumerate(gmap):
        chk(f"api_ok[{fresh.gvk_keys[gm]}]",
            resident.api_ok[g], fresh.api_ok[gm])
    if nB == 0:
        return errs
    # per-binding fields
    for f in ("replicas", "uid_desc", "fresh", "non_workload",
              "nw_shortcut", "b_valid"):
        chk(f, getattr(resident, f)[:nB], getattr(fresh, f)[:nB])
    chk("route", resident.route[:nB], fresh.route[:nB])
    pmap_arr = np.asarray(pmap or [0], np.int32)
    chk("placement_id", resident.placement_id[:nB],
        pmap_arr[fresh.placement_id[:nB]])
    gmap_arr = np.asarray(gmap or [0], np.int32)
    chk("gvk_id", resident.gvk_id[:nB], gmap_arr[fresh.gvk_id[:nB]])
    qmap_arr = np.asarray(qmap or [0], np.int32)
    cid = fresh.class_id[:nB]
    chk("class_id", resident.class_id[:nB],
        np.where(cid >= 0, qmap_arr[np.maximum(cid, 0)], -1))
    ra = _canon_sparse(resident.prev_idx[:nB], resident.prev_val[:nB])
    fa = _canon_sparse(fresh.prev_idx[:nB], fresh.prev_val[:nB])
    if not (np.array_equal(ra[0], fa[0]) and np.array_equal(ra[1], fa[1])):
        errs.append("prev_assignment")
    re_ = _canon_sparse(resident.evict_idx[:nB])
    fe = _canon_sparse(fresh.evict_idx[:nB])
    if not np.array_equal(re_[0], fe[0]):
        errs.append("evict_entries")
    return errs


def _canon_sparse(idx: np.ndarray, val: Optional[np.ndarray] = None):
    """Canonicalize a sparse (idx [B, K], val [B, K]) plane for
    comparison across differing pad widths: rows sorted by lane with -1
    padding last, trimmed to the widest real entry count."""
    idx = np.asarray(idx)
    key = np.where(idx >= 0, idx.astype(np.int64), np.int64(1) << 40)
    order = np.argsort(key, axis=1, kind="stable")
    idx_s = np.take_along_axis(idx, order, axis=1)
    widths = (idx_s >= 0).sum(axis=1)
    w = int(widths.max()) if idx_s.size else 0
    idx_s = idx_s[:, :max(w, 1)]
    if val is None:
        return (idx_s, None)
    val = np.take_along_axis(np.asarray(val), order, axis=1)[:, :max(w, 1)]
    # val is meaningful only where idx >= 0
    val = np.where(idx_s >= 0, val, 0)
    return (idx_s, val)
