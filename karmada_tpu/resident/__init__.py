"""Resident-state plane: device-resident cluster tensors + delta encode.

  state.py    ResidentState — persistent (frozen, copy-on-write) solver
              tensors advanced by deltas, a slot-based per-binding
              encoded-row cache, the bit-exact parity audit, and the
              device mirror plane primed into the solver transfer cache
  deltas.py   DeltaTracker — watch-event ingestion, coalesced per cycle
              and classified capacity / api / structural

Armed by `Scheduler(resident=True)` / `serve --resident` (device backend
only — the native and serial backends never build SolverBatches).  The
active state registers process-wide so /debug/resident (utils/httpserve)
and `karmadactl resident` can publish it without plumbing.
"""

from __future__ import annotations

import threading
from typing import Optional

from karmada_tpu.resident.deltas import CycleDeltas, DeltaTracker  # noqa: F401
from karmada_tpu.resident.state import (  # noqa: F401
    ResidentState,
    RowToken,
    compare_batches,
)

_ACTIVE: Optional[ResidentState] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = threading.Lock()


def set_active(state: Optional[ResidentState]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = state


def active() -> Optional[ResidentState]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def state_payload(recent: int = 0) -> dict:
    """The /debug/resident payload; {"enabled": false} when no resident
    plane is armed so dashboards can poll unconditionally."""
    state = active()
    if state is None:
        return {"enabled": False}
    out = state.stats()
    if recent:
        out["recent_cycles"] = state.recent_cycles(recent)
    return out


def render_state(state: dict) -> str:
    """Human one-screen rendering of a /debug/resident payload
    (karmadactl resident --endpoint)."""
    if not state.get("enabled"):
        return ("no resident-state plane is armed on this plane "
                "(serve --resident with the device backend to arm one)")
    vocab = state.get("vocab") or {}
    audits = state.get("audits") or {}
    last = state.get("last_audit")
    lines = [
        f"resident-state plane: generation {state.get('generation')} "
        f"({'resident' if state.get('resident') else 'rebuild pending'}, "
        f"{state.get('cycles')} cycle(s))",
        f"  vocab: {vocab.get('clusters')} clusters "
        f"({vocab.get('cluster_lanes')} lanes), "
        f"{vocab.get('placements')} placements, "
        f"{vocab.get('classes')} classes, "
        f"{vocab.get('resources')} resources, {vocab.get('gvks')} gvks",
        f"  rows cached {state.get('rows_cached')}; "
        f"hits {state.get('row_hits')} misses {state.get('row_misses')} "
        f"(hit rate {state.get('hit_rate')})",
        f"  rebuilds {state.get('rebuilds')}",
        f"  audits ok={audits.get('ok')} mismatch={audits.get('mismatch')}"
        + (f"; last: cycle {last['cycle']} -> {last['outcome']}"
           + (f" {last['fields']}" if last.get("fields") else "")
           if last else ""),
        f"  device plane {'on' if state.get('device_plane') else 'off'}"
        f" (primed={state.get('device_primed')}); "
        f"last deltas {state.get('last_deltas')}",
    ]
    fused = state.get("fused") or {}
    if fused.get("armed"):
        lines.append(
            f"  fused gather {'on' if fused.get('available') else 'DEGRADED'}"
            f": {fused.get('cycles')} fused / {fused.get('host_cycles')} "
            f"host cycle(s), fallbacks {fused.get('fallbacks')}")
    for rec in state.get("recent_cycles") or ():
        lines.append(
            f"    cycle {rec['cycle']}: {rec['items']} item(s), "
            f"{rec['hits']} hit(s), {rec['misses']} miss(es)"
            + (" [rebuilt]" if rec.get("rebuilt") else ""))
    return "\n".join(lines)
