"""Opportunistic TPU bench watcher.

The tunnel-attached TPU in this environment answers in unpredictable
windows (observed: ~1-2h up, many hours down).  This watcher loops
forever: a cheap out-of-process probe, and the moment the device answers,
the full checkpointed bench (bench.py) fires.  Per-chunk checkpoints mean
a relay drop mid-run keeps everything measured so far; the next window
resumes where the last one died.  The watcher exits when a FRESH on-TPU
full-config result has been captured (bench.py persists it to
bench_ckpt/tpu_latest.json, which the round-end bench reports even if the
chip is down at that moment).

Probe attempts are emitted as structured JSON lines ({"event": "probe",
ts, ok, platform, elapsed_s, rc, err}) so chip-availability trajectory
across rounds is machine-analyzable; narrative events stay human text.

Run detached:  nohup python watch_bench.py > bench_ckpt/watch.log 2>&1 &
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import bench

REPO = os.path.dirname(os.path.abspath(__file__))
PROBE_TIMEOUT_S = 240.0
SLEEP_BETWEEN_PROBES_S = 120.0


def log(msg: str) -> None:
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe_record(probe: dict, attempt: int) -> dict:
    """One probe attempt as a structured record: the chip-availability
    trajectory across rounds is machine-analyzable (grep the watch log
    for '"event": "probe"' and plot ok/elapsed over ts) instead of being
    locked up in free text."""
    last = (probe.get("attempts") or [{}])[-1]
    return {
        "event": "probe",
        "ts": round(time.time(), 3),
        "attempt": attempt,
        "ok": bool(probe.get("ok")),
        "platform": probe.get("platform"),
        # topology: how many chips answered (the mesh-sharded solve's
        # scale axis) — MULTICHIP payloads become self-describing instead
        # of a stderr tail
        "devices": probe.get("device_count"),
        # per-device memory_stats() from the successful probe subprocess
        # (telemetry plane, obs/devprof): HBM visibility across chip
        # windows — null per device on backends that report none
        "memory_stats": probe.get("memory_stats"),
        "elapsed_s": last.get("s"),
        "rc": last.get("rc"),
        "err": (str(last.get("err"))[:200]
                if last.get("err") is not None else None),
    }


def jlog(rec: dict) -> None:
    print(json.dumps(rec), flush=True)


def main() -> int:
    args = sys.argv[1:]  # forwarded to bench.py (e.g. --quick)
    attempt = 0
    while True:
        attempt += 1
        probe = bench.probe_backend(timeout_s=PROBE_TIMEOUT_S)
        jlog(probe_record(probe, attempt))
        if not (probe["ok"] and "tpu" in str(probe["platform"]).lower()):
            time.sleep(SLEEP_BETWEEN_PROBES_S)
            continue
        log(f"probe {attempt}: TPU ANSWERED "
            f"({probe['attempts'][-1]['s']}s, "
            f"{probe.get('device_count') or '?'} device(s)) — launching "
            "bench")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--no-cpu-fallback",
             # the child's probe gets at least the budget the successful
             # watcher probe needed (a slow-answering device must not pass
             # the watcher only to time out in the child every cycle)
             "--probe-timeout", str(int(PROBE_TIMEOUT_S)),
             # watcher mode retries anyway: detect a mid-run relay hang in
             # 5 min (on-chip chunks are seconds; compiles burn CPU and
             # count as progress) instead of the default 10 so a dead
             # window costs one probe cycle less
             "--no-progress-timeout", "300", *args],
            capture_output=True, text=True)
        line = bench._last_json_line((r.stdout or "").splitlines())
        log(f"bench rc={r.returncode}; stderr tail: "
            f"{(r.stderr or '')[-400:]}")
        if line:
            log(f"bench result: {line.strip()[:400]}")
            try:
                payload = json.loads(line)
                detail = payload.get("detail", {})
                if "explain_overhead_pct" in detail:
                    # pass the explain-plane cost fields through as a
                    # structured line (same contract as the probe records)
                    jlog({"event": "explain_overhead",
                          "ts": round(time.time(), 3),
                          "overhead_pct": detail.get("explain_overhead_pct"),
                          "disarmed_delta_pct": detail.get(
                              "explain_disarmed_delta_pct"),
                          "disarmed_new_compiles": detail.get(
                              "explain_disarmed_new_compiles")})
                if "delta" in detail:
                    # resident-plane steady-state summary as a structured
                    # line (bench --delta payloads; the full record is in
                    # detail.delta / the persisted delta_bench.json)
                    dl = detail["delta"]
                    head = (dl.get("churn") or [{}])[0]
                    jlog({"event": "delta",
                          "ts": round(time.time(), 3),
                          "platform": dl.get("platform"),
                          "bindings": dl.get("bindings"),
                          "clusters": dl.get("clusters"),
                          "full_bps": dl.get("full_bps"),
                          "steady_bps": head.get("steady_bps"),
                          "churn_frac": head.get("churn_frac"),
                          "speedup_vs_full": head.get("speedup_vs_full"),
                          "reencode_exact": dl.get("reencode_exact"),
                          "audit_green": dl.get("audit_green"),
                          "parity_ok": dl.get("parity_ok")})
                if "coldstart" in detail:
                    # AOT executable-plane summary as a structured line
                    # (bench --coldstart payloads; the full record is in
                    # detail.coldstart / the persisted coldstart.json)
                    cs = detail["coldstart"]
                    dec = cs.get("decode") or {}
                    jlog({"event": "coldstart",
                          "ts": round(time.time(), 3),
                          "warm_ratio": cs.get("warm_ratio"),
                          "compile_warm_ratio": cs.get("compile_warm_ratio"),
                          "second_misses": cs.get("second_misses"),
                          "first_warmup_s": (cs.get("first") or {}).get(
                              "warmup_s"),
                          "second_warmup_s": (cs.get("second") or {}).get(
                              "warmup_s"),
                          "decode_median_ms": (dec.get("decode_native")
                                               or {}).get("median_ms"),
                          "decode_parity": dec.get(
                              "decode_parity_bit_exact"),
                          "host_budget_bps": dec.get("host_budget_bps")})
                if "soak" in detail:
                    # sustained-traffic SLO summary as a structured line
                    # (bench --soak SCENARIO payloads; the full record is
                    # in detail.soak / the persisted soak_*.json)
                    soak = detail["soak"]
                    jlog({"event": "soak",
                          "ts": round(time.time(), 3),
                          "scenario": soak.get("scenario"),
                          "injected": soak.get("injected"),
                          "scheduled": soak.get("scheduled"),
                          "p99_latency_s": soak.get(
                              "schedule_latency_s", {}).get("p99"),
                          "p99_dwell_s": soak.get(
                              "queue_dwell_s", {}).get("p99"),
                          "admission": soak.get("admission"),
                          "overload": soak.get(
                              "starvation", {}).get("overload_entered")})
                if "facade" in detail:
                    # facade coalescing summary as a structured line
                    # (bench --facade payloads; the full record is in
                    # detail.facade / the persisted facade.json)
                    fc = detail["facade"]
                    jlog({"event": "facade",
                          "ts": round(time.time(), 3),
                          "callers": fc.get("callers"),
                          "batches": fc.get("batches"),
                          "coalesce_ratio": fc.get("coalesce_ratio"),
                          "speedup_x": fc.get("speedup_x"),
                          "whatif_isolated": fc.get("whatif_isolated")})
                if "incremental" in detail:
                    # dirty-set steady-state summary as a structured line
                    # (bench --incremental payloads; the full record is
                    # in detail / the persisted MEGAFLEET_r02.json)
                    inc = detail["incremental"]
                    jlog({"event": "incremental",
                          "ts": round(time.time(), 3),
                          "adopt_s": inc.get("adopt_s"),
                          "steady_p50_s": inc.get("steady_p50_s"),
                          "steady_p99_s": inc.get("steady_p99_s"),
                          "dirty_rows_mean": inc.get("dirty_rows_mean"),
                          "speedup_x": inc.get("speedup_x"),
                          "audit_outcome": inc.get("audit_outcome"),
                          "fallbacks": inc.get("fallbacks"),
                          "chunk_drag_rows": inc.get("chunk_drag_rows")})
                led = ((detail.get("soak") or {}).get("events")
                       or (detail.get("chaos") or {}).get("events")
                       or (detail.get("rebalance") or {}).get("events"))
                if led:
                    # lifecycle-ledger pass-through (obs/events): the
                    # run's event-rate / coalesce summary as a
                    # structured line, same contract as soak/slo
                    jlog({"event": "ledger",
                          "ts": round(time.time(), 3),
                          "recorded": led.get("recorded"),
                          "events_per_s": led.get("events_per_s"),
                          "coalesce_ratio": led.get("coalesce_ratio"),
                          "evicted": led.get("evicted"),
                          "by_reason": led.get("by_reason")})
                inc_sum = ((detail.get("soak") or {}).get("incidents")
                           or (detail.get("chaos") or {}).get("incidents")
                           or (detail.get("rebalance") or {})
                           .get("incidents"))
                if inc_sum:
                    # incident-plane pass-through (obs/incidents): the
                    # run's capture/suppression summary as a structured
                    # line, same contract as ledger/slo
                    jlog({"event": "incident",
                          "ts": round(time.time(), 3),
                          "captured": inc_sum.get("captured"),
                          "suppressed": inc_sum.get("suppressed"),
                          "by_trigger": inc_sum.get("by_trigger"),
                          "cooldown_s": inc_sum.get("cooldown_s"),
                          "incidents": [
                              {"id": e.get("id"),
                               "trigger": e.get("trigger"),
                               "summary": e.get("summary")}
                              for e in (inc_sum.get("incidents")
                                        or [])[:8]]})
                slo_v = (detail.get("slo")
                         or (detail.get("soak") or {}).get("slo")
                         or ((detail.get("chaos") or {}).get("slo"))
                         or ((detail.get("rebalance") or {}).get("slo")))
                if slo_v:
                    # SLO verdict pass-through (telemetry plane): the
                    # burn-rate summary as a structured line, same
                    # contract as the soak/delta/coldstart events
                    jlog({"event": "slo",
                          "ts": round(time.time(), 3),
                          "healthy": slo_v.get("healthy"),
                          "window": slo_v.get("window"),
                          "objectives": {
                              o["name"]: {"healthy": o.get("healthy"),
                                          "burn": o.get("burn_rate"),
                                          "budget": o.get(
                                              "budget_remaining")}
                              for o in slo_v.get("objectives", [])},
                          "regression": slo_v.get("regression")})
                live_tpu = ("tpu" in str(detail.get("platform", "")).lower()
                            and not detail.get("cached"))
                if live_tpu and payload.get("value", 0) > 0:
                    log("fresh on-TPU measurement captured; persisted to "
                        "bench_ckpt/tpu_latest.json — watcher done")
                    return 0
            except json.JSONDecodeError:
                pass
        log("no fresh TPU result this window; finished chunks are "
            "checkpointed — retrying")
        time.sleep(SLEEP_BETWEEN_PROBES_S)


if __name__ == "__main__":
    raise SystemExit(main())
