"""Device-probe failure must reroute to the fastest working backend.

VERDICT r4: `serve --backend device` falling back to XLA:CPU (12x slower
than the native C++ pipeline on the bench workload) is operationally wrong
— the batched scheduler must never be slower than the serial loop it
replaces (reference pkg/scheduler/core/generic_scheduler.go:71-116).
These tests drive utils/deviceprobe.resolve_backend with injected probes
(no real backend is touched) and the serve loader end to end.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from karmada_tpu import native  # noqa: E402
from karmada_tpu.utils import deviceprobe  # noqa: E402


def probe_of(ok, platform):
    calls = []

    def probe(timeout_s):
        calls.append(timeout_s)
        return {"ok": ok, "platform": platform,
                "attempts": [{"ok": ok, "s": 0.1}]}
    probe.calls = calls
    return probe


def test_non_device_backends_skip_the_probe():
    for req in ("native", "serial"):
        probe = probe_of(True, "tpu")
        backend, diag = deviceprobe.resolve_backend(req, probe=probe)
        assert backend == req
        assert probe.calls == []
        assert diag == {"probed": False}


def test_live_accelerator_keeps_device_backend():
    for platform in ("tpu", "TPU v4", "gpu", "cuda"):
        backend, diag = deviceprobe.resolve_backend(
            "device", probe=probe_of(True, platform))
        assert backend == "device"
        assert "degraded" not in diag


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_dead_probe_reroutes_to_native():
    backend, diag = deviceprobe.resolve_backend(
        "device", probe=probe_of(False, None))
    assert backend == "native"
    assert "rerouting to backend=native" in diag["degraded"]


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_cpu_only_probe_reroutes_to_native():
    """A probe that ANSWERS but with the host CPU is still a reroute: the
    XLA program on CPU is the slowest available engine for this work."""
    backend, diag = deviceprobe.resolve_backend(
        "device", probe=probe_of(True, "cpu"))
    assert backend == "native"
    assert "no accelerator" in diag["degraded"]


def test_dead_probe_without_toolchain_lands_on_serial(monkeypatch):
    monkeypatch.setattr(native, "available", lambda: False)
    backend, diag = deviceprobe.resolve_backend(
        "device", probe=probe_of(False, None))
    assert backend == "serial"
    assert "rerouting to backend=serial" in diag["degraded"]


def test_working_cpu_without_toolchain_keeps_device(monkeypatch):
    """XLA works (on host CPU) and there is no native toolchain: the XLA
    program still beats the pure-Python serial loop, so the device backend
    stays — rerouting to something SLOWER would invert the policy's
    purpose."""
    monkeypatch.setattr(native, "available", lambda: False)
    backend, diag = deviceprobe.resolve_backend(
        "device", probe=probe_of(True, "cpu"))
    assert backend == "device"
    assert "degraded" not in diag


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_serve_loader_reroutes_on_dead_probe(tmp_path, monkeypatch, capsys):
    """The serve path end to end: a dead probe must hand the ControlPlane a
    native-backend scheduler, loudly."""
    from karmada_tpu import cli

    monkeypatch.setattr(
        deviceprobe, "probe_backend",
        lambda timeout_s: {"ok": False, "platform": None, "attempts": [
            {"ok": False, "s": timeout_s,
             "err": "probe timed out (backend init hang)"}]})
    cp = cli._load_plane(str(tmp_path / "plane"), backend="device",
                         probe_device=True, probe_timeout=1.0)
    assert cp.scheduler.backend == "native"
    assert "rerouting to backend=native" in capsys.readouterr().err


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_hung_device_cycle_degrades_scheduler_mid_serve(monkeypatch, capsys):
    """The startup probe cannot catch a tunnel that dies MID-serve: a
    device cycle exceeding the guard timeout must be abandoned on its
    thread and the scheduler degraded one-way to the native backend —
    with the batch still scheduled (by native) in the SAME cycle."""
    import threading

    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.scheduler import service as svc
    from karmada_tpu.scheduler.metrics import BACKEND_DEGRADED

    cp = ControlPlane(backend="device", device_cycle_timeout_s=0.3)
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()

    hang = threading.Event()

    def stuck_solve(self, items, clusters, cancelled=None, **_kw):
        hang.wait(30)  # the XLA dispatch never returns
        return {}

    monkeypatch.setattr(svc.Scheduler, "_solve_device", stuck_solve)
    before = BACKEND_DEGRADED.value(to="native")

    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement, PropagationPolicy, PropagationSpec, ResourceSelector,
    )

    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(),
        ),
    ))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 2, "template": {"spec": {"containers": [
                  {"name": "a", "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()
    hang.set()  # release the zombie thread

    assert cp.scheduler.backend == "native"
    assert BACKEND_DEGRADED.value(to="native") == before + 1
    rb = cp.store.get("ResourceBinding", "default", "app-deployment")
    assert rb.spec.clusters, "the degraded cycle must still schedule"
    assert "degrading the scheduler to backend=native" in capsys.readouterr().err


def test_serve_loader_skips_probe_when_disabled(tmp_path):
    """--no-probe (tests / known-good hardware): the requested backend is
    honored without spending a probe."""
    from karmada_tpu import cli

    def boom(timeout_s):  # pragma: no cover - must never run
        raise AssertionError("probe ran despite probe_device=False")

    import karmada_tpu.utils.deviceprobe as dp
    orig = dp.probe_backend
    dp.probe_backend = boom
    try:
        cp = cli._load_plane(str(tmp_path / "plane"), backend="device",
                             probe_device=False)
    finally:
        dp.probe_backend = orig
    assert cp.scheduler.backend == "device"
