import pytest

from karmada_tpu.models import Cluster, ResourceBinding
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.store import Event, ObjectStore
from karmada_tpu.store.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from karmada_tpu.store.worker import AsyncWorker, Runtime


def _cluster(name: str) -> Cluster:
    return Cluster(metadata=ObjectMeta(name=name))


def test_create_get_list():
    s = ObjectStore()
    s.create(_cluster("m1"))
    s.create(_cluster("m2"))
    assert s.get("Cluster", "", "m1").name == "m1"
    assert [c.name for c in s.list("Cluster")] == ["m1", "m2"]
    with pytest.raises(AlreadyExistsError):
        s.create(_cluster("m1"))
    with pytest.raises(NotFoundError):
        s.get("Cluster", "", "nope")


def test_resource_version_and_generation():
    s = ObjectStore()
    c = s.create(_cluster("m1"))
    rv0, gen0 = c.metadata.resource_version, c.metadata.generation
    assert gen0 == 1
    c.spec.region = "us-east"
    c2 = s.update(c)
    assert c2.metadata.resource_version > rv0
    assert c2.metadata.generation == gen0 + 1
    # status-only change does not bump generation
    c2.status.kubernetes_version = "1.30"
    c3 = s.update(c2)
    assert c3.metadata.generation == c2.metadata.generation


def test_conflict_on_stale_update():
    s = ObjectStore()
    c = s.create(_cluster("m1"))
    stale = s.get("Cluster", "", "m1")
    c.spec.region = "a"
    s.update(c)
    stale.spec.region = "b"
    with pytest.raises(ConflictError):
        s.update(stale)


def test_mutate_retries():
    s = ObjectStore()
    s.create(_cluster("m1"))
    s.mutate("Cluster", "", "m1", lambda c: setattr(c.spec, "region", "r1"))
    assert s.get("Cluster", "", "m1").spec.region == "r1"


def test_watch_events():
    s = ObjectStore()
    events: list[Event] = []
    s.bus.subscribe(events.append, kind="Cluster")
    c = s.create(_cluster("m1"))
    c.spec.region = "r"
    c = s.update(c)
    s.delete("Cluster", "", "m1")
    assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]


def test_finalizer_gated_delete():
    s = ObjectStore()
    c = _cluster("m1")
    c.metadata.finalizers = ["karmada.io/cluster-controller"]
    c = s.create(c)
    s.delete("Cluster", "", "m1")
    obj = s.get("Cluster", "", "m1")  # still present
    assert obj.metadata.deleting
    obj.metadata.finalizers = []
    s.update(obj)
    assert s.try_get("Cluster", "", "m1") is None


def test_worker_dedup_and_retry():
    seen = []

    def reconcile(key):
        seen.append(key)
        if len(seen) == 1:
            raise RuntimeError("transient")
        return None

    w = AsyncWorker("t", reconcile, max_retries=3)
    rt = Runtime()
    rt.register(w)
    w.enqueue("a")
    w.enqueue("a")  # dedup
    rt.pump()
    assert seen == ["a", "a"]  # failed once, retried once


def test_binding_store_roundtrip():
    s = ObjectStore()
    rb = ResourceBinding(metadata=ObjectMeta(name="web-abc", namespace="default"))
    rb.spec.replicas = 3
    s.create(rb)
    got = s.get("ResourceBinding", "default", "web-abc")
    assert got.spec.replicas == 3
