"""Mesh-sharded production solve (ops/meshing + ops/solver).

The scheduler hot path must produce BIT-IDENTICAL results with a
(bindings, clusters) device mesh active — sharding changes nothing but
the wall clock.  Covered here, on the conftest's 8-virtual-CPU-device
platform (2-device meshes for tier-1 speed; the full 8-device parity run
is `slow`, and __graft_entry__.dryrun_multichip covers the driver path):

  * parity: run_pipeline under an active mesh vs the single-device path
    on mixed routes (device, region-spread, big-tier, host rows), the
    big lane tier, and a multi-chunk vocabulary-GAP carry;
  * the no-op fallback: shape off / 1x1 / one device activates nothing
    and the solver dispatch path is byte-identical to the pre-mesh one;
  * buffer donation (the carry used0 micro-fix): the donated dispatch
    engages on the chain, the chain still yields sequential-equivalent
    pricing, and the nnz bound refuses donation when escalation is
    possible;
  * observability: karmada_mesh_* gauges and the /debug/state mesh
    section reflect activation;
  * plumbing: Scheduler(mesh_shape=) end to end through the ControlPlane.

conftest caveat (utils/jaxenv.py): the suite pins EIGHT virtual devices
before jax initialises; a later force_cpu(n_devices=2) re-pin is a no-op
by design (the backend already satisfies >= 2 CPU devices), so 2-device
meshes here are built over jax.devices()[:2] of the 8-device platform —
the exact pattern __graft_entry__.dryrun_multichip(2) uses.
"""

import random
import sys

import numpy as np
import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.ops import meshing, serial, tensors
from karmada_tpu.scheduler import pipeline
from karmada_tpu.utils.metrics import REGISTRY

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_pipeline_executor import (  # noqa: E402
    _fleet,
    _mixed_items,
    _results_equal,
)

GVK = ("apps/v1", "Deployment")


@pytest.fixture(autouse=True)
def _no_mesh_leak():
    """Every test leaves the process-wide mesh deactivated: other test
    modules assume the single-device dispatch path."""
    yield
    meshing.deactivate()


def _activate_2dev(shape=(1, 2)):
    import jax

    plan = meshing.activate(shape, devices=jax.devices()[:2])
    assert plan is not None
    return plan


def _run(items, cindex, est, **kw):
    kw.setdefault("chunk", 4)
    kw.setdefault("waves", 2)
    kw.setdefault("carry", True)
    return pipeline.run_pipeline(items, cindex, est, **kw)


# -- shape parsing / fallback -------------------------------------------------

def test_parse_shape():
    assert meshing.parse_shape("2x4") == (2, 4)
    assert meshing.parse_shape("1X2") == (1, 2)
    assert meshing.parse_shape((2, 4)) == (2, 4)
    assert meshing.parse_shape("auto") == "auto"
    for off in (None, "", "off", "none", "1x1", "1", (1, 1)):
        assert meshing.parse_shape(off) is None
    for bad in ("2x", "x4", "0x4", "2x4x1", "fast", "axb", (0, 4)):
        with pytest.raises(ValueError, match="mesh"):
            meshing.parse_shape(bad)


def test_single_device_fallback_is_noop():
    """shape off / 1x1 / a one-device pool activates nothing, and the
    solver's arg placement is the identical pre-mesh path (raw numpy
    binding args — no committed device arrays, no new jit signatures)."""
    import jax

    from karmada_tpu.ops import solver

    assert meshing.activate("off") is None
    assert meshing.activate((1, 1)) is None
    assert meshing.activate("auto", devices=jax.devices()[:1]) is None
    assert meshing.active() is None
    assert meshing.mesh_info() == {"enabled": False, "shape": None,
                                   "devices": 1, "platform": None}

    clusters, cindex = _fleet(8)
    batch = tensors.encode_batch(_mixed_items()[:2], cindex,
                                 GeneralEstimator())
    args = solver._batch_args(batch)  # noqa: SLF001
    # binding-axis operands stay the raw numpy arrays (zero added
    # dispatch overhead on the fallback path)
    assert args[-1] is batch.evict_idx
    assert args[-12] is batch.b_valid


def test_activate_requires_enough_devices():
    import jax

    with pytest.raises(RuntimeError):
        meshing.activate((2, 4), devices=jax.devices()[:2])


def test_scheduler_falls_back_when_mesh_exceeds_devices():
    """An explicit mesh_shape larger than the device pool must not crash
    the control plane: the first device cycle's activation attempt warns
    and the scheduler runs single-device (activation is deferred to the
    guarded solve path — never __init__, where a dead-tunnel jax init
    would hang the plane's startup)."""
    from karmada_tpu.e2e import ControlPlane

    cp = ControlPlane(backend="device", mesh_shape="16x16")
    assert cp.scheduler.mesh_plan is None  # nothing activated at init
    cp.scheduler._ensure_mesh()  # noqa: SLF001 — first device cycle
    assert cp.scheduler.mesh_plan is None
    assert meshing.active() is None


def test_reactivation_relabels_device_gauge():
    """Re-activating with a new shape must zero the old gauge label —
    /metrics must never report two meshes as simultaneously active."""
    import jax

    meshing.activate((1, 2), devices=jax.devices())
    meshing.activate((2, 1), devices=jax.devices())
    assert meshing.MESH_DEVICES.value(shape="1x2", platform="cpu") == 0.0
    assert meshing.MESH_DEVICES.value(shape="2x1", platform="cpu") == 2.0
    meshing.deactivate()


# -- parity: sharded vs single-device ----------------------------------------

def test_mesh_parity_mixed_routes():
    """run_pipeline under a 2-device cluster-sharded mesh must be
    bit-identical to the single-device path on the mixed-route matrix
    (plain strategies, region spread, host rows)."""
    clusters, cindex = _fleet(24)
    est = GeneralEstimator()
    items = _mixed_items()

    want = _run(items, cindex, est)
    assert want.results, "reference run scheduled nothing"

    _activate_2dev((1, 2))  # shard the cluster axis: the collective path
    got = _run(items, cindex, est)
    meshing.deactivate()

    assert set(got.results) == set(want.results)
    for i in sorted(want.results):
        _results_equal(want.results[i], got.results[i], ctx=f"binding {i}")

    # and the binding (data-parallel) axis
    _activate_2dev((2, 1))
    got2 = _run(items, cindex, est)
    assert set(got2.results) == set(want.results)
    for i in sorted(want.results):
        _results_equal(want.results[i], got2.results[i], ctx=f"binding {i}")


def test_mesh_parity_big_tier():
    """ROUTE_DEVICE_BIG rows (the big lane tier, C beyond COMPACT_LANES)
    must survive sharding bit for bit — the big sub-solve dispatches
    through the same mesh-aware path."""
    rng = random.Random(3)
    clusters = bench.build_fleet(rng, 560)  # pads to C=1024 > COMPACT_LANES
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()
    # big/small mix: replicas > COMPACT_DIVISION_CAP routes to the big tier
    from karmada_tpu.models.policy import (
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        REPLICA_DIVISION_WEIGHTED,
        REPLICA_SCHEDULING_DIVIDED,
        REPLICA_SCHEDULING_DUPLICATED,
        ClusterPreferences,
        Placement,
        ReplicaSchedulingStrategy,
    )
    from karmada_tpu.models.work import (
        ObjectReference,
        ReplicaRequirements,
        ResourceBindingSpec,
        ResourceBindingStatus,
    )
    from karmada_tpu.utils.quantity import Quantity

    def binding(b, replicas, divided=True):
        pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=(REPLICA_SCHEDULING_DIVIDED if divided
                                     else REPLICA_SCHEDULING_DUPLICATED),
            replica_division_preference=(REPLICA_DIVISION_WEIGHTED
                                         if divided else None),
            weight_preference=(ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)
                if divided else None)))
        return (
            ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"a{b}",
                                         uid=f"u{b}"),
                replicas=replicas,
                replica_requirements=ReplicaRequirements(resource_request={
                    "cpu": Quantity.from_milli(100)}),
                placement=pl),
            ResourceBindingStatus(),
        )

    items = [binding(0, 80), binding(1, 2, divided=False), binding(2, 82),
             binding(3, 2, divided=False)]
    batch = tensors.encode_batch(items, cindex, est)
    assert (batch.route == tensors.ROUTE_DEVICE_BIG).sum() == 2

    # waves=2 exercises the sharded wave scan through the big lane tier
    want = _run(items, cindex, est, chunk=2, waves=2)
    _activate_2dev((1, 2))
    got = _run(items, cindex, est, chunk=2, waves=2)
    assert set(got.results) == set(want.results)
    for i in sorted(want.results):
        _results_equal(want.results[i], got.results[i], ctx=f"binding {i}")


def _capacity_builders():
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_contention import mk_binding, mk_cluster

    return mk_cluster, mk_binding


def test_mesh_vocabulary_gap_carry():
    """The chunk-to-chunk carry chain must stay exact under a mesh across
    a vocabulary GAP (chunk 1's encoding drops the consumed resource):
    the keyed CarryState re-render and the device-side remap both operate
    on sharded accumulators."""
    mk_cluster, mk_binding = _capacity_builders()
    est = GeneralEstimator()
    clusters = [mk_cluster("m1", cpu_milli=10**9, mem_units=10,
                           pods=10**6)]
    cindex = tensors.ClusterIndex.build(clusters)

    def mem(bi, rep):
        return mk_binding(bi, replicas=rep, cpu_milli=10, mem_units=1)

    def cpu_only(bi, rep):
        s, st = mk_binding(bi, replicas=rep, cpu_milli=10, mem_units=0)
        s.replica_requirements.resource_request.pop("memory")
        return s, st

    items = [mem(0, 8), cpu_only(1, 5), mem(2, 8)]
    _activate_2dev((1, 2))
    res = pipeline.run_pipeline(items, cindex, est, chunk=1, waves=1,
                                carry=True)
    assert not isinstance(res.results[0], Exception)
    assert not isinstance(res.results[1], Exception)
    # chunk 0 consumed all 8 memory units; chunk 2 must see that through
    # the gap even though every accumulator in between lived mesh-sharded
    assert isinstance(res.results[2], serial.UnschedulableError)

    # growth leg: vocabulary gains a resource mid-cycle (device remap)
    clusters2 = [mk_cluster("m1", cpu_milli=1000, mem_units=10**6,
                            pods=10**6)]
    cindex2 = tensors.ClusterIndex.build(clusters2)
    a = mk_binding(0, replicas=8, cpu_milli=100, mem_units=0)
    c = mk_binding(2, replicas=1, cpu_milli=100, mem_units=1)
    b = mk_binding(1, replicas=8, cpu_milli=100, mem_units=0)
    res2 = pipeline.run_pipeline([a, c, b], cindex2, est, chunk=1, waves=1,
                                 carry=True)
    assert not isinstance(res2.results[0], Exception)
    assert not isinstance(res2.results[1], Exception)
    assert isinstance(res2.results[2], serial.UnschedulableError)


def test_mesh_tiny_chunk_waves_fallback():
    """A chunk whose per-wave row count cannot fill the bindings mesh
    axis (Bw=1: one-binding waves on a tiny control plane — the exact
    `serve --mesh 2x4` startup shape) must dispatch unsharded via
    ops/solver._plan_for and still match the mesh-off result; chunks
    whose Bw divides keep the mesh."""
    import jax

    from karmada_tpu.ops import solver

    rng = random.Random(1)
    clusters = bench.build_fleet(rng, 4)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 4, placements)  # pads to B=8
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)

    plan = meshing.activate((2, 4), devices=jax.devices())
    # Bw = 8/8 = 1 < bindings axis 2: this dispatch must fall back ...
    assert solver._plan_for(batch, 8) is None  # noqa: SLF001
    # ... while a divisible wave count keeps the mesh
    assert solver._plan_for(batch, 4) is plan  # noqa: SLF001

    got8 = solver.solve_compact(batch, waves=8)
    got4 = solver.solve_compact(batch, waves=4)
    meshing.deactivate()
    ref8 = solver.solve_compact(batch, waves=8)
    ref4 = solver.solve_compact(batch, waves=4)
    for got, ref in ((got8, ref8), (got4, ref4)):
        assert got[3] == ref[3]
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])
        np.testing.assert_array_equal(got[2], ref[2])


@pytest.mark.slow
def test_mesh_parity_eight_devices():
    """Full 8-device 2x4 mesh over the bench mix — heavier (the virtual
    CPU mesh emulates collectives by thread rendezvous), so `slow`."""
    rng = random.Random(0)
    clusters = bench.build_fleet(rng, 24)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 32, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)

    want = _run(items, cindex, est, chunk=16, waves=2)
    plan = meshing.activate((2, 4))
    assert plan is not None and plan.n_devices == 8
    got = _run(items, cindex, est, chunk=16, waves=2)
    assert set(got.results) == set(want.results)
    for i in sorted(want.results):
        _results_equal(want.results[i], got.results[i], ctx=f"binding {i}")


# -- buffer donation ----------------------------------------------------------

def test_donation_chain_sequential_equivalent():
    """The donated carry dispatch must leave the chain's pricing exactly
    sequential-equivalent: chunked execution at one binding per wave with
    chunk-to-chunk carry equals ONE compact solve at one binding per wave
    — and donation must actually have engaged."""
    from karmada_tpu.ops.solver import DONATED_DISPATCHES, solve_compact

    rng = random.Random(2)
    clusters = bench.build_fleet(rng, 32)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 64, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    b0 = tensors.encode_batch(items, cindex, est)
    dev_items = [items[i] for i in range(len(items))
                 if b0.route[i] == tensors.ROUTE_DEVICE][:32]
    assert len(dev_items) == 32

    batch = tensors.encode_batch(dev_items, cindex, est)
    i1, v1, s1, _ = solve_compact(batch, waves=len(dev_items))
    ref = tensors.decode_compact(batch, i1, v1, s1)

    before = DONATED_DISPATCHES.value()
    res = pipeline.run_pipeline(dev_items, cindex, est, chunk=8, waves=8,
                                carry=True)
    assert DONATED_DISPATCHES.value() > before, \
        "donation never engaged on an escalation-free carry chain"
    for j in range(len(dev_items)):
        _results_equal(ref[j], res.results[j], ctx=f"binding {j}")


def test_donation_handle_flag_and_deletion():
    """Direct handle-level contract: a donated chain dispatch marks its
    handle, deletes the upstream used-out buffers once consumed, and
    finalize_compact reports the donated-away used tuple as None."""
    from karmada_tpu.ops.solver import dispatch_compact, dispatched_used, \
        finalize_compact

    mk_cluster, mk_binding = _capacity_builders()
    clusters = [mk_cluster("m1", cpu_milli=10**6, mem_units=10**6,
                           pods=10**6)]
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()
    batch = tensors.encode_batch(
        [mk_binding(0, replicas=2, cpu_milli=10, mem_units=1)], cindex, est)

    h1 = dispatch_compact(batch, waves=1, with_used=True,
                          used0=None, donate_used0=False)
    assert h1[9] is False  # no used0: nothing to donate
    used1 = dispatched_used(h1)
    h2 = dispatch_compact(batch, waves=1, with_used=True,
                          used0=used1, donate_used0=True)
    assert h2[9] is True
    assert all(u.is_deleted() for u in used1)
    fin1 = finalize_compact(h1)
    assert fin1[4] is None  # donated downstream: not materializable
    fin2 = finalize_compact(h2)
    assert fin2[4] is not None  # chain head: still live


def test_donation_refused_when_escalation_possible():
    """_nnz_bound must refuse donation whenever the extraction could
    overflow a sub-dense cap — the escalation re-solve needs the donated
    operands back.  The bound is per-row replicas (not a tier cap):
    small fleets (C <= COMPACT_LANES, compact=False encoding) route
    Divided rows of ANY replica count to the device."""
    from types import SimpleNamespace

    from karmada_tpu.ops.solver import _nnz_bound
    from karmada_tpu.ops.tensors import STRAT_DUPLICATED, STRAT_DYNAMIC

    def fake(n_dup, n_div, C=2048, Kp=4, replicas=10):
        B = n_dup + n_div
        strat = np.array([STRAT_DUPLICATED] * n_dup
                         + [STRAT_DYNAMIC] * n_div, np.int32)
        return SimpleNamespace(
            C=C,
            pl_strategy=strat,
            placement_id=np.arange(B, dtype=np.int32),
            b_valid=np.ones(B, bool),
            non_workload=np.zeros(B, bool),
            replicas=np.full(B, replicas, np.int64),
            prev_idx=np.full((B, Kp), -1, np.int32),
        )

    assert _nnz_bound(fake(n_dup=0, n_div=10)) == 10 * (10 + 4)
    # big Divided rows on a small fleet (the compact=False class): each
    # can seat up to min(replicas, C) lanes — no 64-seat cap applies
    assert _nnz_bound(fake(n_dup=0, n_div=200, C=512, replicas=100)) \
        == 200 * (100 + 4)
    # replicas beyond the fleet clamp at C
    assert _nnz_bound(fake(n_dup=0, n_div=2, C=512, replicas=10**6)) \
        == 2 * (512 + 4)
    # 10 duplicated rows can each legitimately fill the cluster axis
    assert _nnz_bound(fake(n_dup=10, n_div=0)) == 10 * 2048
    # ... which exceeds the default sub-dense cap, so a dispatch with
    # max_nnz = 16384 < bound must NOT donate
    assert _nnz_bound(fake(n_dup=10, n_div=0)) > 16384


# -- observability + plumbing -------------------------------------------------

def test_mesh_gauges_and_debug_state():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    plan = _activate_2dev((1, 2))
    assert meshing.MESH_ENABLED.value() == 1.0
    assert meshing.MESH_DEVICES.value(shape="1x2", platform="cpu") == 2.0
    assert 'karmada_mesh_devices{shape="1x2",platform="cpu"}' \
        in REGISTRY.dump()
    state = ObservabilityServer()._state()  # noqa: SLF001
    assert state["mesh"] == {
        "enabled": True, "shape": "1x2", "devices": 2, "platform": "cpu",
        "axes": {"bindings": 1, "clusters": 2}}
    assert plan.shape_str == "1x2"

    meshing.deactivate()
    assert meshing.MESH_ENABLED.value() == 0.0
    assert meshing.MESH_DEVICES.value(shape="1x2", platform="cpu") == 0.0
    assert ObservabilityServer()._state()["mesh"]["enabled"] is False  # noqa: SLF001


def test_scheduler_mesh_plumbing_end_to_end():
    """ControlPlane(mesh_shape=) reaches ops/meshing through the
    scheduler, and a device-backend cycle schedules every binding with
    the mesh active."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement,
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.models.work import ResourceBinding

    cp = ControlPlane(backend="device", pipeline_chunk=4, mesh_shape="1x2")
    try:
        # activation is deferred to the first device solve (never
        # __init__: a dead-tunnel jax init must not hang plane startup)
        assert cp.scheduler.mesh_plan is None
        for i in range(3):
            cp.add_member(f"m{i}", cpu_milli=64_000)
        cp.tick()
        cp.apply_policy(PropagationPolicy(
            metadata=ObjectMeta(name="pp", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(api_version=GVK[0],
                                                     kind=GVK[1])],
                placement=Placement())))
        for i in range(8):
            cp.apply({"apiVersion": GVK[0], "kind": GVK[1],
                      "metadata": {"namespace": "default", "name": f"d{i}"},
                      "spec": {"replicas": 2}})
        cp.tick()
        rbs = cp.store.list(ResourceBinding.KIND)
        assert len(rbs) == 8
        assert all(rb.spec.clusters for rb in rbs)
        # the first device cycle activated the mesh
        assert cp.scheduler.mesh_plan is not None
        assert meshing.active() is cp.scheduler.mesh_plan
    finally:
        meshing.deactivate()


def test_serve_mesh_flag_parse():
    """`serve --mesh BxC` parses through cli._load_plane's vocabulary."""
    from karmada_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["--dir", "/tmp/x", "serve", "--mesh", "2x4"])
    assert args.mesh == "2x4"
    assert meshing.parse_shape(args.mesh) == (2, 4)
    args2 = build_parser().parse_args(["--dir", "/tmp/x", "serve"])
    assert meshing.parse_shape(args2.mesh) is None
