"""Golden tests: batched TPU Webster kernel vs the serial dispenser.

Every case asserts bit-identical seat vectors between ops/solver.webster_divide
and ops/webster.allocate_webster_seats (the faithful port of reference
pkg/util/helper/webstermethod.go:112 + binding.go:70-144).
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from karmada_tpu.ops.solver import webster_divide, webster_divide_batch
from karmada_tpu.ops.webster import allocate_webster_seats, dispense_by_weight


def run_kernel(n, votes, init=None, descending=False, pad_to=None):
    """Run webster_divide over a name-keyed problem; returns {name: seats}."""
    names = sorted(set(votes) | set(init or {}))
    C = pad_to or len(names)
    w = np.zeros(C, np.int64)
    s0 = np.zeros(C, np.int64)
    active = np.zeros(C, bool)
    order = sorted(names, reverse=descending)
    rank = np.zeros(C, np.int64)
    for i, name in enumerate(names):
        w[i] = votes.get(name, 0)
        s0[i] = (init or {}).get(name, 0)
        active[i] = True
        rank[i] = order.index(name)
    # padding lanes get distinct high ranks
    rank[len(names):] = np.arange(len(names), C)
    seats = np.asarray(
        webster_divide(jnp.int64(n), jnp.asarray(w), jnp.asarray(s0),
                       jnp.asarray(active), jnp.asarray(rank))
    )
    return {name: int(seats[i]) for i, name in enumerate(names)}


def serial(n, votes, init=None, descending=False):
    parties = allocate_webster_seats(n, votes, init, descending)
    return {p.name: p.seats for p in parties}


def test_simple_proportional():
    votes = {"a": 100, "b": 50, "c": 25}
    assert run_kernel(7, votes) == serial(7, votes)


def test_exact_ties_name_ascending():
    votes = {"a": 10, "b": 10, "c": 10}
    assert run_kernel(4, votes) == serial(4, votes)
    assert run_kernel(4, votes) == {"a": 2, "b": 1, "c": 1}


def test_exact_ties_name_descending():
    votes = {"a": 10, "b": 10, "c": 10}
    assert run_kernel(4, votes, descending=True) == serial(4, votes, descending=True)
    assert run_kernel(4, votes, descending=True) == {"a": 1, "b": 1, "c": 2}


def test_initial_seats_kept():
    votes = {"a": 5, "b": 5}
    init = {"a": 3, "c": 2}  # c has zero votes: keeps seats, never awarded
    got = run_kernel(4, votes, init)
    assert got == serial(4, votes, init)
    assert got["c"] == 2


def test_zero_total_weight_awards_nothing():
    votes = {"a": 0, "b": 0}
    init = {"a": 2}
    assert run_kernel(5, votes, init) == {"a": 2, "b": 0}


def test_zero_seats():
    votes = {"a": 7, "b": 3}
    assert run_kernel(0, votes, {"a": 1}) == {"a": 1, "b": 0}


def test_single_party():
    assert run_kernel(9, {"solo": 1}) == {"solo": 9}


def test_large_seat_count_fast_forward():
    """Bisection must fast-forward: 100k seats cannot run 100k iterations."""
    votes = {"a": 997, "b": 601, "c": 89, "d": 11}
    got = run_kernel(100_000, votes)
    assert got == serial(100_000, votes)
    assert sum(got.values()) == 100_000


def test_padding_lanes_inert():
    votes = {"a": 10, "b": 7}
    assert run_kernel(5, votes, pad_to=16) == serial(5, votes)


@pytest.mark.parametrize("seed", range(30))
def test_property_random(seed):
    rng = random.Random(seed)
    n_parties = rng.randint(1, 12)
    names = [f"c{i:02d}" for i in range(n_parties)]
    # bias toward ties: draw from a small value set half the time
    if rng.random() < 0.5:
        pool = [rng.randint(0, 20) for _ in range(3)]
        votes = {nm: rng.choice(pool) for nm in names}
    else:
        votes = {nm: rng.randint(0, 10_000) for nm in names}
    init = {}
    if rng.random() < 0.5:
        for nm in rng.sample(names, rng.randint(0, n_parties)):
            init[nm] = rng.randint(0, 5)
    n = rng.randint(0, 200)
    desc = rng.random() < 0.5
    got = run_kernel(n, votes, init, desc, pad_to=16)
    want = serial(n, votes, init, desc)
    for nm in names:
        assert got[nm] == want.get(nm, 0), (seed, n, votes, init, desc, got, want)


def test_batch_vmap():
    B, C = 8, 6
    rng = np.random.default_rng(0)
    n = rng.integers(0, 50, size=B).astype(np.int64)
    w = rng.integers(0, 100, size=(B, C)).astype(np.int64)
    s0 = rng.integers(0, 3, size=(B, C)).astype(np.int64)
    active = np.ones((B, C), bool)
    rank = np.tile(np.arange(C, dtype=np.int64), (B, 1))
    seats = np.asarray(
        webster_divide_batch(jnp.asarray(n), jnp.asarray(w), jnp.asarray(s0),
                             jnp.asarray(active), jnp.asarray(rank))
    )
    names = [f"c{i}" for i in range(C)]
    for b in range(B):
        votes = {names[i]: int(w[b, i]) for i in range(C)}
        init = {names[i]: int(s0[b, i]) for i in range(C) if s0[b, i]}
        want = dispense_by_weight(int(n[b]), votes, init, "")
        # dispense returns init-only when total weight is zero
        for i, nm in enumerate(names):
            expect = want.get(nm, init.get(nm, 0)) if want else init.get(nm, 0)
            assert int(seats[b, i]) == expect, (b, votes, init, int(n[b]))


def test_large_initial_seats_regression():
    """Bisection count() must clamp to n AFTER subtracting initial seats;
    a large s0 once made the kernel award seats the serial dispenser never
    gives (kernel {a:266,b:34} vs serial {a:300,b:0})."""
    votes = {"a": 1000, "b": 1}
    init = {"a": 100}
    got = run_kernel(200, votes, init)
    assert got == serial(200, votes, init)
    assert got == {"a": 300, "b": 0}


@pytest.mark.parametrize("seed", range(8))
def test_property_large_init(seed):
    rng = random.Random(1000 + seed)
    names = [f"c{i}" for i in range(rng.randint(1, 6))]
    votes = {nm: rng.randint(0, 5000) for nm in names}
    init = {nm: rng.randint(0, 500) for nm in rng.sample(names, rng.randint(1, len(names)))}
    n = rng.randint(0, 800)
    got = run_kernel(n, votes, init, pad_to=8)
    want = serial(n, votes, init)
    for nm in names:
        assert got[nm] == want.get(nm, 0), (seed, n, votes, init, got, want)
