"""Golden tests for spread-constraint group selection, transcribed from
reference pkg/scheduler/core/spreadconstraint/select_groups_test.go and
select_clusters_by_cluster/region semantics."""

import pytest

from karmada_tpu.models.cluster import Cluster, ClusterSpec
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import ResourceBindingSpec, TargetCluster
from karmada_tpu.ops.serial import (
    ClusterDetailInfo,
    GroupClustersInfo,
    GroupInfo,
    UnschedulableError,
    _DfsGroup,
    select_best_clusters,
    select_groups,
)


def g(name, value, weight):
    return _DfsGroup(name=name, value=value, weight=weight)


@pytest.mark.parametrize(
    "groups,min_c,max_c,target,expected",
    [
        ([], 2, 3, 1, []),
        ([g("R1", 1, 80)], 2, 3, 1, []),
        ([g("R1", 1, 80), g("R2", 2, 30)], 2, 3, 4, []),
        ([g("R1", 1, 80)], 1, 3, 1, ["R1"]),
        (
            [g("R1", 1, 80), g("R3", 1, 80), g("R2", 1, 60), g("R5", 2, 60),
             g("R4", 5, 50), g("R6", 3, 50)],
            1, 3, 10, ["R5", "R4", "R6"],
        ),
        (
            [g("R1", 1, 80), g("R2", 4, 40), g("R3", 1, 30), g("R4", 3, 30),
             g("R5", 3, 20), g("R6", 5, 10)],
            2, 6, 5, ["R1", "R2"],
        ),
        (
            [g("R1", 1, 60), g("R2", 1, 50), g("R3", 1, 40), g("R4", 3, 30),
             g("R5", 3, 20), g("R6", 3, 10)],
            1, 3, 6, ["R1", "R4", "R5"],
        ),
        (
            [g("R1", 1, 60), g("R2", 2, 50), g("R3", 3, 40), g("R4", 4, 30)],
            1, 2, 5, ["R1", "R4"],
        ),
        (
            [g("R4", 1, 60), g("R3", 3, 50), g("R1", 3, 40), g("R2", 4, 30)],
            1, 2, 5, ["R3", "R1"],
        ),
    ],
)
def test_select_groups_golden(groups, min_c, max_c, target, expected):
    got = [grp.name for grp in select_groups(groups, min_c, max_c, target)]
    assert got == expected


# --- selectBestClustersByCluster -------------------------------------------


def detail(name, score, available):
    return ClusterDetailInfo(
        name=name,
        score=score,
        available_replicas=available,
        allocatable_replicas=available,
        cluster=Cluster(metadata=ObjectMeta(name=name)),
    )


def duplicated_placement(min_groups, max_groups):
    return Placement(
        spread_constraints=[
            SpreadConstraint(
                spread_by_field="cluster", min_groups=min_groups, max_groups=max_groups
            )
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Duplicated"
        ),
    )


def divided_placement(min_groups, max_groups):
    return Placement(
        spread_constraints=[
            SpreadConstraint(
                spread_by_field="cluster", min_groups=min_groups, max_groups=max_groups
            )
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Aggregated",
        ),
    )


def test_select_by_cluster_duplicated_takes_top_scored():
    # Duplicated ignores available resource: top MaxGroups by sort order
    info = GroupClustersInfo(
        clusters=[detail("m1", 60, 40), detail("m2", 50, 30), detail("m3", 40, 60)]
    )
    got = select_best_clusters(duplicated_placement(1, 2), info, 80)
    assert [c.name for c in got] == ["m1", "m2"]


def test_select_by_cluster_capacity_repair():
    # select_clusters_by_cluster.go:49-57 example: member1+member3 win because
    # member1+member2 lack capacity for needReplicas=80.
    info = GroupClustersInfo(
        clusters=[detail("m1", 60, 40), detail("m2", 50, 30), detail("m3", 40, 60)]
    )
    got = select_best_clusters(divided_placement(1, 2), info, 80)
    assert {c.name for c in got} == {"m1", "m3"}


def test_select_by_cluster_min_groups_unsatisfied():
    info = GroupClustersInfo(clusters=[detail("m1", 60, 40)])
    with pytest.raises(UnschedulableError):
        select_best_clusters(duplicated_placement(2, 3), info, 10)


def test_select_by_cluster_insufficient_capacity():
    info = GroupClustersInfo(
        clusters=[detail("m1", 60, 10), detail("m2", 50, 10), detail("m3", 40, 10)]
    )
    with pytest.raises(UnschedulableError):
        select_best_clusters(divided_placement(1, 2), info, 80)


def test_no_spread_constraints_returns_all():
    info = GroupClustersInfo(
        clusters=[detail("m1", 60, 40), detail("m2", 50, 30)]
    )
    got = select_best_clusters(Placement(), info, 10)
    assert [c.name for c in got] == ["m1", "m2"]


# --- selectBestClustersByRegion ---------------------------------------------


def region_info(regions):
    """regions: {name: (score, [ClusterDetailInfo])}"""
    info = GroupClustersInfo()
    for name, (score, clusters) in regions.items():
        info.regions[name] = GroupInfo(
            name=name,
            score=score,
            available_replicas=sum(c.available_replicas for c in clusters),
            clusters=clusters,
        )
        info.clusters.extend(clusters)
    return info


def region_placement(r_min, r_max, c_min=0, c_max=0):
    scs = [
        SpreadConstraint(spread_by_field="region", min_groups=r_min, max_groups=r_max)
    ]
    if c_min or c_max:
        scs.append(
            SpreadConstraint(spread_by_field="cluster", min_groups=c_min, max_groups=c_max)
        )
    return Placement(
        spread_constraints=scs,
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Duplicated"
        ),
    )


def test_select_by_region_picks_best_cluster_per_region():
    info = region_info(
        {
            "r1": (80, [detail("a1", 60, 10), detail("a2", 50, 10)]),
            "r2": (60, [detail("b1", 40, 10), detail("b2", 30, 10)]),
        }
    )
    got = select_best_clusters(region_placement(2, 2, 1, 2), info, 5)
    assert {c.name for c in got} == {"a1", "b1"}


def test_select_by_region_fills_extra_clusters():
    info = region_info(
        {
            "r1": (80, [detail("a1", 60, 10), detail("a2", 50, 99)]),
            "r2": (60, [detail("b1", 40, 10)]),
        }
    )
    got = select_best_clusters(region_placement(2, 2, 1, 3), info, 5)
    assert [c.name for c in got][:2] == ["a1", "b1"]
    assert {c.name for c in got} == {"a1", "b1", "a2"}


def test_select_by_region_min_groups_unsatisfied():
    info = region_info({"r1": (80, [detail("a1", 60, 10)])})
    with pytest.raises(UnschedulableError):
        select_best_clusters(region_placement(2, 3, 1, 2), info, 5)
