"""Incident plane (obs/incidents): flight recorder, trigger bus, and
forensic bundle capture.

Covers the ISSUE-20 acceptance surface: the armed-by-default per-cycle
flight ring (scheduler + incremental records, bounded, one-list-read
disarmed), the typed trigger bus with per-kind cooldown rate limiting
on an injectable clock, self-contained JSON bundles (flight ring +
telemetry/SLO + implicated timelines + locks block + trigger detail)
persisted under <dir>/incidents, the /debug/incidents[/{id}] endpoints,
and the soak contracts: injected faults (degrade, audit divergence,
lock-watchdog trip, cycle fault) each yield one rate-limited bundle,
while a healthy steady soak yields none.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import pytest

from karmada_tpu import chaos
from karmada_tpu.obs import events as obs_events
from karmada_tpu.obs import incidents

pytestmark = pytest.mark.incidents


@pytest.fixture(autouse=True)
def _fresh_plane():
    """Every test gets a fresh flight ring and no armed store; none may
    leak an armed incident store (or chaos plane) into the suite."""
    incidents.configure_flight()
    yield
    incidents.disarm()
    incidents.configure_flight()
    chaos.disarm()


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_disarmable():
    rec = incidents.configure_flight(capacity=4)
    for i in range(10):
        assert incidents.record("cycle", cycle_id=i)
    st = rec.stats()
    assert st == {"recorded": 10, "retained": 4, "capacity": 4}
    snap = rec.snapshot()
    assert [r["cycle_id"] for r in snap] == [6, 7, 8, 9]  # oldest first
    assert all(r["kind"] == "cycle" for r in snap)
    assert rec.snapshot(2) == snap[-2:] and rec.snapshot(0) == []
    incidents.arm_flight(False)
    try:
        assert not incidents.record("cycle", cycle_id=99)
        assert rec.stats()["recorded"] == 10  # disarmed: nothing lands
    finally:
        incidents.arm_flight(True)


def test_scheduler_cycle_emits_flight_records():
    """The live scheduler cycle lands one kind="cycle" record with the
    batch/cut/backend/queue-depth forensics the bundles snapshot."""
    import tests.test_chaos as tc

    store, rt, sched = tc._slice(backend="serial")  # noqa: SLF001
    for i in range(3):
        store.create(tc.build_binding(f"fl-b{i}"))
    rt.pump()
    recs = [r for r in incidents.flight().snapshot()
            if r["kind"] == "cycle"]
    assert recs, "scheduler cycle recorded no flight record"
    fr = recs[-1]
    assert fr["batch"] == 3 and fr["cut"] in ("window", "deadline", "drain")
    assert fr["backend"] == "serial" and fr["fault"] is None
    assert fr["scheduled"] == 3 and fr["errors"] == 0
    assert fr["cycle_id"] >= 1 and fr["elapsed_s"] >= 0
    assert "active" in fr["depths"] and "active" in fr["oldest_s"]


def test_incremental_cycle_emits_flight_records():
    import tests.test_incremental_solve as tinc
    from karmada_tpu.estimator.general import GeneralEstimator
    from karmada_tpu.resident import ResidentState
    from karmada_tpu.resident.deltas import CycleDeltas
    from karmada_tpu.scheduler.incremental import IncrementalSolver

    _rng, clusters, _names, _pls, bindings = tinc._world(  # noqa: SLF001
        n_clusters=16, n_bindings=48, seed=5)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=32,
                               audit_every=0)
    solver.adopt(clusters, bindings)
    solver.write_back()
    solver.cycle(clusters, bindings, CycleDeltas(), force_audit=True)
    recs = [r for r in incidents.flight().snapshot()
            if r["kind"] == "incremental"]
    assert recs, "incremental cycle recorded no flight record"
    fr = recs[-1]
    assert fr["total"] == 48 and fr["mode"] == "incremental"
    assert fr["audited"] is True and fr["audit_outcome"] == "ok"
    assert fr["dirty"] >= 0 and isinstance(fr["groups"], list)


# ---------------------------------------------------------------------------
# trigger bus + bundle capture (the check.sh smoke leg)
# ---------------------------------------------------------------------------


def test_trigger_bundle_smoke(tmp_path):
    """One trigger end to end: a complete self-contained bundle on disk
    and in the index, the metrics moved, the cooldown suppressing the
    repeat, and the capture announced on the lifecycle ledger."""
    incidents.record("cycle", cycle_id=7, batch=3)
    obs_events.emit_key(("inc", "b0"), obs_events.TYPE_NORMAL,
                        obs_events.REASON_BINDING_ENQUEUED, "enqueued")
    d = str(tmp_path / "incidents")
    incidents.configure(d, cooldown_s=60.0, clock=_Clock())
    c0 = incidents.INCIDENTS.total()
    s0 = incidents.INCIDENTS_SUPPRESSED.total()
    iid = incidents.trigger(
        incidents.TRIGGER_CYCLE_FAULT, "cycle fault contained (Boom)",
        refs=[("inc", "b0")], detail={"kind": "Boom", "cycle_id": 7})
    assert iid is not None
    # rate limit: same kind inside the cooldown is suppressed
    assert incidents.trigger(incidents.TRIGGER_CYCLE_FAULT, "again") is None
    assert incidents.INCIDENTS.total() == c0 + 1
    assert incidents.INCIDENTS_SUPPRESSED.total() == s0 + 1
    bundle = incidents.bundle_payload(iid)
    assert bundle is not None and "capture_errors" not in bundle
    assert bundle["trigger"] == "cycle-fault"
    assert bundle["detail"] == {"kind": "Boom", "cycle_id": 7}
    # complete artifacts: every forensic section landed
    assert any(r["cycle_id"] == 7 for r in bundle["flight"]["records"])
    assert "samples" in bundle["telemetry"]
    assert "enabled" in bundle["slo"]
    assert "locks" in bundle["locks"] or "enabled" in bundle["locks"]
    tl = bundle["timelines"]["inc/b0"]
    assert any(e["reason"] == obs_events.REASON_BINDING_ENQUEUED
               for e in tl)
    assert isinstance(bundle["recent_events"], list)
    # persisted, self-contained, and announced
    path = bundle["path"]
    assert path and os.path.exists(path) and path.startswith(d)
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["id"] == iid
    recent = obs_events.state_payload(n=8)["recent"]
    assert any(e.get("reason") == obs_events.REASON_INCIDENT_CAPTURED
               for e in recent), recent
    # the index reflects both the capture and the suppression
    state = incidents.state_payload()
    assert state["enabled"] and state["captured"] == 1
    assert state["by_trigger"] == {"cycle-fault": 1}
    assert state["suppressed"] == {"cycle-fault": 1}
    assert [e["id"] for e in state["incidents"]] == [iid]


def test_disarmed_trigger_is_noop_smoke():
    c0 = incidents.INCIDENTS.total()
    assert incidents.active() is None
    assert incidents.trigger(incidents.TRIGGER_BACKEND_DEGRADE, "x") is None
    assert incidents.INCIDENTS.total() == c0
    state = incidents.state_payload()
    assert state["enabled"] is False and "flight" in state


def test_unknown_trigger_kind_rejected():
    store = incidents.configure(None, clock=_Clock())
    with pytest.raises(AssertionError):
        store.trigger("not-a-kind", "x")


def test_cooldown_is_per_kind_on_injected_clock():
    clock = _Clock(t=1000.0)
    incidents.configure(None, cooldown_s=60.0, clock=clock)
    assert incidents.trigger(incidents.TRIGGER_CYCLE_FAULT, "a")
    # an unrelated kind has its own cooldown window
    assert incidents.trigger(incidents.TRIGGER_BACKEND_DEGRADE, "b")
    assert incidents.trigger(incidents.TRIGGER_CYCLE_FAULT, "c") is None
    clock.t += 61.0
    assert incidents.trigger(incidents.TRIGGER_CYCLE_FAULT, "d")
    state = incidents.state_payload()
    assert state["by_trigger"] == {"cycle-fault": 2, "backend-degrade": 1}
    assert state["suppressed"] == {"cycle-fault": 1}


def test_bundle_index_bounded_with_disk_fallback(tmp_path):
    clock = _Clock()
    incidents.configure(str(tmp_path), cooldown_s=0.0, keep=2,
                        clock=clock)
    ids = []
    for kind in (incidents.TRIGGER_CYCLE_FAULT,
                 incidents.TRIGGER_BACKEND_DEGRADE,
                 incidents.TRIGGER_LOCK_WATCHDOG):
        clock.t += 1.0
        ids.append(incidents.trigger(kind, "x"))
    state = incidents.state_payload()
    assert [e["id"] for e in state["incidents"]] == ids[1:]  # keep=2
    # the evicted bundle is still readable from its on-disk artifact
    evicted = incidents.bundle_payload(ids[0])
    assert evicted is not None and evicted["id"] == ids[0]


# ---------------------------------------------------------------------------
# detector wiring: one injected fault per trigger kind -> one bundle
# ---------------------------------------------------------------------------


def test_cycle_fault_trigger_captures_bundle():
    import tests.test_chaos as tc

    incidents.configure(None, cooldown_s=3600.0)
    store, rt, sched = tc._slice()  # noqa: SLF001
    for i in range(2):
        store.create(tc.build_binding(f"icf-b{i}"))
    chaos.configure("device.dispatch:raise#1")
    rt.pump()
    rt.tick()
    state = incidents.state_payload()
    assert state["by_trigger"].get("cycle-fault") == 1, state["by_trigger"]
    iid = state["incidents"][-1]["id"]
    bundle = incidents.bundle_payload(iid)
    assert bundle["detail"]["kind"] == "ChaosFault"
    # the implicated bindings' timelines rode along
    assert any(k.endswith("icf-b0") for k in bundle["timelines"]), (
        list(bundle["timelines"]))


def test_backend_degrade_trigger_captures_bundle():
    import time

    import tests.test_chaos as tc

    incidents.configure(None, cooldown_s=3600.0)
    store, rt, sched = tc._slice(device_cycle_timeout_s=None,
                                 device_recover_cycles=1)
    store.create(tc.build_binding("idg-warm"))
    rt.pump()  # unguarded: pays the jit compile
    sched.device_cycle_timeout_s = 0.5
    chaos.configure("device.cycle:hang:1.5#1")
    store.create(tc.build_binding("idg-b1"))
    rt.pump()
    rt.tick()
    state = incidents.state_payload()
    assert state["by_trigger"].get("backend-degrade") == 1, (
        state["by_trigger"])
    bundle = incidents.bundle_payload(state["incidents"][-1]["id"])
    assert bundle["trigger"] == "backend-degrade"
    assert bundle["detail"]["to"] in ("native", "serial")
    time.sleep(1.2)  # give the abandoned zombie its sleep back


def test_audit_divergence_trigger_captures_diff_bundle():
    import tests.test_incremental_solve as tinc
    from karmada_tpu.estimator.general import GeneralEstimator
    from karmada_tpu.resident import ResidentState
    from karmada_tpu.resident.deltas import CycleDeltas
    from karmada_tpu.scheduler.incremental import IncrementalSolver

    incidents.configure(None, cooldown_s=3600.0)
    _rng, clusters, _names, _pls, bindings = tinc._world(  # noqa: SLF001
        n_clusters=32, n_bindings=128, seed=37)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    tinc._settle(solver, clusters, bindings)  # noqa: SLF001
    pos = next(p for p, r in solver.results.items()
               if not isinstance(r, Exception))
    solver.results[pos] = []  # diverged state (placements dropped)
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.audit_outcome == "mismatch"
    st = incidents.state_payload()
    assert st["by_trigger"].get("audit-divergence") == 1, st["by_trigger"]
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    detail = bundle["detail"]
    assert detail["n_bad"] >= 1 and detail["ledger_ok"] in (True, False)
    # the divergence diff names the row and both answers
    row = next(r for r in detail["rows"]
               if r["key"] == solver.keys[pos])
    assert not row["incremental"] and row["control"]


def test_lock_watchdog_trigger_captures_bundle():
    from karmada_tpu.analysis import guards
    from karmada_tpu.utils import locks

    incidents.configure(None, cooldown_s=3600.0)
    was = guards.armed()
    guards.arm()
    lock = locks.VetLock("incidents.wd-test")
    try:
        with lock:
            trips = locks.LockWatchdog(threshold_s=0.0).check()
    finally:
        guards.arm(was)
    assert any(t["lock"] == "incidents.wd-test" for t in trips)
    st = incidents.state_payload()
    assert st["by_trigger"].get("lock-watchdog") == 1, st["by_trigger"]
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    assert any(t["lock"] == "incidents.wd-test"
               for t in bundle["detail"]["trips"])


def test_lock_inversion_trigger_captures_bundle():
    from karmada_tpu.analysis import guards
    from karmada_tpu.utils import locks

    incidents.configure(None, cooldown_s=3600.0)
    locks.reset_for_tests()
    was = guards.armed()
    guards.arm()
    la = locks.VetLock("incidents.inv-a")
    lb = locks.VetLock("incidents.inv-b")
    try:
        with la:
            with lb:
                pass
        with lb:
            with la:  # the reverse edge: an order inversion
                pass
    finally:
        guards.arm(was)
        locks.reset_for_tests()
    st = incidents.state_payload()
    assert st["by_trigger"].get("lock-inversion") == 1, st["by_trigger"]
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    assert bundle["detail"]["pair"] == "incidents.inv-a|incidents.inv-b"


def test_invariant_violation_trigger():
    from karmada_tpu.analysis.guards import InvariantViolation

    incidents.configure(None, cooldown_s=3600.0)
    with pytest.raises(InvariantViolation):
        raise InvariantViolation("bench: d2h poisoned row")
    st = incidents.state_payload()
    assert st["by_trigger"].get("invariant-violation") == 1
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    assert "poisoned" in bundle["detail"]["message"]


def test_slo_unhealthy_edge_triggers_once():
    """The SLO trigger fires on the healthy->unhealthy TRANSITION, not
    per unhealthy window (cooldown 0 here, so a refire would capture)."""
    import tests.test_telemetry as tt
    from karmada_tpu.obs import slo as obs_slo

    incidents.configure(None, cooldown_s=0.0)
    obj = obs_slo.Objective("errs", "ratio", target=0.99,
                            bad=("karmada_test_bad_total", None),
                            total=("karmada_test_all_total", None))
    ev = obs_slo.SloEvaluator(objectives=[obj], short_frac=0.25)
    burning = [(float(i), tt._counter_snap(i * 2.0, i * 100.0))  # noqa: SLF001
               for i in range(8)]
    assert ev.evaluate(tt._FakeRing(burning))["healthy"] is False  # noqa: SLF001
    ev.evaluate(tt._FakeRing(burning))  # still unhealthy: no refire  # noqa: SLF001
    st = incidents.state_payload()
    assert st["by_trigger"] == {"slo-unhealthy": 1}, st["by_trigger"]
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    assert bundle["detail"]["unhealthy"] == ["errs"]


def test_regression_watchdog_trip_edge_triggers():
    from karmada_tpu.obs import slo as obs_slo

    incidents.configure(None, cooldown_s=0.0)
    wd = obs_slo.RegressionWatchdog(baseline_bps=1000.0)
    ev = obs_slo.SloEvaluator(objectives=[], watchdog=wd)
    ev.evaluate(_EmptyRing())     # not tripped: quiet
    wd.tripped = True             # injected trip (check() keeps it on
    ev.evaluate(_EmptyRing())     # a <2-sample window): the edge fires
    ev.evaluate(_EmptyRing())     # still tripped: no refire
    st = incidents.state_payload()
    assert st["by_trigger"] == {"regression-watchdog": 1}, st["by_trigger"]
    bundle = incidents.bundle_payload(st["incidents"][-1]["id"])
    assert bundle["detail"]["baseline_bps"] == 1000.0


class _EmptyRing:
    def samples(self, n=None):
        return []


def test_safety_violation_reason_and_trigger():
    """The satellite fix: SafetyAuditor violations land on the ledger
    (REASON_SafetyViolation, keyed by invariant) and fire the incident
    trigger — not only the bench payload."""
    from karmada_tpu.chaos import audit as chaos_audit

    incidents.configure(None, cooldown_s=3600.0)
    chaos_audit.surface_violations([
        {"kind": "double-placed", "binding": "loadgen/dp-b0",
         "detail": "2 live placements"},
        {"kind": "double-placed", "binding": "loadgen/dp-b1",
         "detail": "2 live placements"},
        {"kind": "recovery-missed",
         "detail": "the backend degraded and never re-armed"},
    ])
    # the implicated binding's own timeline carries the invariant key
    tl = obs_events.timeline_payload("loadgen", "dp-b0")
    assert any(e["reason"] == obs_events.REASON_SAFETY_VIOLATION
               for e in tl["events"]), tl["events"]
    # the cooldown admits ONE safety-violation bundle; the second
    # invariant kind inside the window is suppressed, not a storm
    st = incidents.state_payload()
    assert st["by_trigger"] == {"safety-violation": 1}
    assert st["suppressed"] == {"safety-violation": 1}
    bundle = incidents.bundle_payload(st["incidents"][0]["id"])
    assert bundle["detail"]["kind"] == "double-placed"
    assert bundle["detail"]["count"] == 2
    assert "loadgen/dp-b0" in bundle["timelines"]


# ---------------------------------------------------------------------------
# endpoints + CLI surface
# ---------------------------------------------------------------------------


def _fetch(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_incidents_endpoints(tmp_path):
    from karmada_tpu.utils.httpserve import ObservabilityServer

    incidents.record("cycle", cycle_id=3)
    incidents.configure(str(tmp_path / "incidents"), cooldown_s=0.0,
                        clock=_Clock())
    iid = incidents.trigger(incidents.TRIGGER_SLO_UNHEALTHY,
                            "p99 budget burned")
    srv = ObservabilityServer()
    base = srv.start()
    try:
        status, body = _fetch(base + "/debug/incidents")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] and payload["captured"] == 1
        assert payload["incidents"][0]["id"] == iid
        status, body = _fetch(base + f"/debug/incidents/{iid}")
        assert status == 200
        bundle = json.loads(body)
        assert bundle["trigger"] == "slo-unhealthy"
        assert any(r["cycle_id"] == 3 for r in bundle["flight"]["records"])
        status, body = _fetch(base + "/debug/incidents/nope")
        assert status == 404 and "nope" in json.loads(body)["error"]
    finally:
        srv.stop()


def test_debug_incidents_disarmed_payload():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    srv = ObservabilityServer()
    base = srv.start()
    try:
        status, body = _fetch(base + "/debug/incidents")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is False and "flight" in payload
    finally:
        srv.stop()


def test_cli_incidents_and_describe_incident(tmp_path, capsys):
    from karmada_tpu import cli
    from karmada_tpu.utils.httpserve import ObservabilityServer

    incidents.configure(str(tmp_path / "incidents"), cooldown_s=0.0,
                        clock=_Clock())
    iid = incidents.trigger(incidents.TRIGGER_BACKEND_DEGRADE,
                            "device backend degraded to serial")
    srv = ObservabilityServer()
    base = srv.start()
    try:
        assert cli.main(["incidents", "--endpoint", base]) == 0
        out = capsys.readouterr().out
        assert iid in out and "backend-degrade" in out
        assert cli.main(["describe", "incident", iid,
                         "--endpoint", base]) == 0
        out = capsys.readouterr().out
        assert json.loads(out)["id"] == iid
        assert cli.main(["incidents", iid, "--endpoint", base]) == 0
        assert json.loads(capsys.readouterr().out)["id"] == iid
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# trace_id propagation across the facade wire (satellite)
# ---------------------------------------------------------------------------


def test_wire_trace_id_round_trip_and_frame_compat():
    from karmada_tpu.estimator import wire

    bare = wire.AssignReplicasRequest(namespace="ns", name="b")
    assert "traceId" not in bare.to_json()  # untraced frame unchanged
    req = wire.AssignReplicasRequest(namespace="ns", name="b",
                                     trace_id="t-123")
    d = req.to_json()
    assert d["traceId"] == "t-123"
    assert wire.AssignReplicasRequest.from_json(d).trace_id == "t-123"
    # default-tolerant: frames from older peers parse
    assert wire.AssignReplicasRequest.from_json(
        {"name": "b"}).trace_id == ""


def test_facade_batch_stitches_caller_trace_ids():
    import tests.test_facade as tf

    plane, _, _ = tf._slice()  # noqa: SLF001
    svc = tf._service(plane, batch_window=1)  # noqa: SLF001
    try:
        req = tf._assign_req("inc-tr-caller")  # noqa: SLF001
        req.trace_id = "caller-abc"
        resp = svc.assign(req)
        assert resp.outcome == "scheduled"
    finally:
        svc.close()
    recs = [r for r in incidents.flight().snapshot()
            if r["kind"] == "facade"]
    assert recs, "facade dispatch recorded no flight record"
    assert recs[-1]["caller_trace_ids"] == ["caller-abc"]
    assert recs[-1]["batch"] == 1


# ---------------------------------------------------------------------------
# soak contracts: chaos yields bundles, healthy steady yields none
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.soak
def test_chaos_soak_yields_rate_limited_bundles():
    """The compressed chaos soak's injected faults (device hang ->
    degrade, device dispatch raise -> contained cycle fault) each yield
    exactly ONE bundle under a run-spanning cooldown, complete with the
    flight ring, and the SOAK payload embeds the incident summary."""
    import tests.test_chaos as tc

    incidents.configure(None, cooldown_s=1e9)
    plane, driver, p = tc._run_chaos_soak()  # noqa: SLF001
    by_trigger = incidents.state_payload()["by_trigger"]
    assert by_trigger.get("backend-degrade") == 1, by_trigger
    assert by_trigger.get("cycle-fault") == 1, by_trigger
    # the injected resident corruption forced a dense-audit divergence
    assert by_trigger.get("audit-divergence") == 1, by_trigger
    # every bundle is complete: flight ring + telemetry + locks rode
    for entry in incidents.state_payload()["incidents"]:
        bundle = incidents.bundle_payload(entry["id"])
        assert "capture_errors" not in bundle, bundle["capture_errors"]
        assert bundle["flight"]["records"], entry
    # the soak report embeds the summary (watch_bench pass-through)
    assert p["incidents"]["by_trigger"] == by_trigger
    assert p["incidents"]["captured"] == sum(by_trigger.values())


@pytest.mark.soak
def test_healthy_steady_soak_yields_zero_bundles():
    import tests.test_loadgen_soak as tls

    incidents.configure(None, cooldown_s=0.0)
    _scenario, _driver, p = tls.run_scenario("steady")
    state = incidents.state_payload()
    assert state["captured"] == 0, state["by_trigger"]
    assert state["suppressed"] == {}
    assert p["incidents"]["captured"] == 0
    # the flight ring still recorded the healthy cycles
    assert state["flight"]["recorded"] > 0
