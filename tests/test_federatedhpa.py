"""FederatedHPA + CronFederatedHPA end to end.

Reference: pkg/controllers/federatedhpa/federatedhpa_controller.go:141-995,
replica_calculator.go, cronfederatedhpa/cronfederatedhpa_controller.go:58.

The closed loop under test: member load changes -> metrics provider merges
pod samples across the workload's target clusters -> replica calculator ->
template spec.replicas -> detector refreshes the binding -> scheduler
redistributes.
"""

import pytest

from karmada_tpu.controllers.federatedhpa import (
    RETAIN_REPLICAS_LABEL,
    cron_matches,
)
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.autoscaling import (
    CronFederatedHPA,
    CronFederatedHPARule,
    CronFederatedHPASpec,
    CrossVersionObjectReference,
    FederatedHPA,
    FederatedHPASpec,
    HPABehavior,
    HPAScalingPolicy,
    HPAScalingRules,
    MetricSpec,
    MetricTarget,
    ResourceMetricSource,
)
from karmada_tpu.models.meta import ObjectMeta, deep_get
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def deployment(replicas=4):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    )


def hpa(min_r=2, max_r=10, target_util=50, behavior=None):
    return FederatedHPA(
        metadata=ObjectMeta(name="web-hpa", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            min_replicas=min_r,
            max_replicas=max_r,
            metrics=[MetricSpec(resource=ResourceMetricSource(
                name="cpu",
                target=MetricTarget(type="Utilization", average_utilization=target_util),
            ))],
            behavior=behavior,
        ),
    )


@pytest.fixture
def env():
    clock = FakeClock()
    cp = ControlPlane(backend="serial", clock=clock)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.store.create(policy())
    cp.apply(deployment())
    # steady state: 50m usage on a 100m request == exactly the 50% target,
    # so the HPA holds the initial 4 replicas until a test drives the load
    for m in cp.members.values():
        m.set_load("Deployment", "default", "web", {"cpu": 50})
    cp.store.create(hpa())
    cp.tick()
    assert template_replicas(cp) == 4
    return cp, clock


def set_load_everywhere(cp, cpu):
    for m in cp.members.values():
        m.set_load("Deployment", "default", "web", {"cpu": cpu})


def template_replicas(cp):
    obj = cp.store.get("Deployment", "default", "web")
    return int(deep_get(obj.manifest, "spec.replicas", 0))


def test_scale_up_on_load_then_down_when_idle(env):
    cp, clock = env
    # 90m usage on a 100m request vs 50% target -> ratio 1.8 -> scale up
    set_load_everywhere(cp, 90)
    cp.tick()
    up = template_replicas(cp)
    assert up > 4, f"expected scale-up, got {up}"
    # binding follows (detector + scheduler closed the loop)
    rb = cp.store.get(ResourceBinding.KIND, "default", "web-deployment")
    assert sum(tc.replicas for tc in rb.spec.clusters) == up

    # drop to idle; the 300s down-stabilization window must hold first
    set_load_everywhere(cp, 10)
    cp.tick()
    assert template_replicas(cp) == up, "scaled down inside stabilization window"
    clock.advance(400)
    cp.tick()
    cp.tick()
    down = template_replicas(cp)
    assert down < up, f"expected scale-down after window, got {down}"
    assert down >= 2


def test_scale_respects_max_replicas(env):
    cp, clock = env
    set_load_everywhere(cp, 10_000)
    cp.tick()
    clock.advance(60)
    cp.tick()
    clock.advance(60)
    cp.tick()
    assert template_replicas(cp) <= 10
    h = cp.store.get(FederatedHPA.KIND, "default", "web-hpa")
    assert h.status.desired_replicas <= 10


def test_tolerance_holds_steady(env):
    cp, clock = env
    # 52m vs 50% of 100m target: ratio 1.04, inside the 10% tolerance
    set_load_everywhere(cp, 52)
    cp.tick()
    assert template_replicas(cp) == 4


def test_behavior_pods_policy_limits_step(env):
    cp, clock = env
    b = HPABehavior(scale_up=HPAScalingRules(
        stabilization_window_seconds=0,
        policies=[HPAScalingPolicy(type="Pods", value=1, period_seconds=60)],
    ))
    def set_behavior(h):
        h.spec.behavior = b
    cp.store.mutate(FederatedHPA.KIND, "default", "web-hpa", set_behavior)
    set_load_everywhere(cp, 10_000)
    cp.tick()
    assert template_replicas(cp) == 5  # one pod per step


def test_scale_target_marker_labels_template(env):
    """Propagating a NATIVE HorizontalPodAutoscaler marks its scale target
    with retain-replicas, so members keep their own replica counts."""
    cp, _ = env
    assert cp.store.get("Deployment", "default", "web").metadata.labels.get(
        RETAIN_REPLICAS_LABEL) is None  # FederatedHPA path: unmarked
    cp.apply({
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web-native-hpa", "namespace": "default"},
        "spec": {"scaleTargetRef": {"apiVersion": "apps/v1",
                                    "kind": "Deployment", "name": "web"},
                 "minReplicas": 1, "maxReplicas": 10},
    })
    cp.tick()
    obj = cp.store.get("Deployment", "default", "web")
    assert obj.metadata.labels.get(RETAIN_REPLICAS_LABEL) == "true"


def test_cron_matches_basics():
    import time as _t
    # 2026-01-05 is a Monday; 10:30 local
    ts = _t.mktime((2026, 1, 5, 10, 30, 0, 0, 0, -1))
    assert cron_matches("30 10 * * *", ts)
    assert cron_matches("*/15 * * * *", ts)
    assert cron_matches("30 10 5 1 1", ts)
    assert not cron_matches("31 10 * * *", ts)
    assert not cron_matches("30 10 * * 0", ts)


def test_cron_scales_workload_on_schedule(env):
    cp, clock = env
    cp.store.create(CronFederatedHPA(
        metadata=ObjectMeta(name="nightly", namespace="default"),
        spec=CronFederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            rules=[CronFederatedHPARule(
                name="every-minute", schedule="* * * * *", target_replicas=7)],
        ),
    ))
    cp.tick()  # first sync registers; rules fire only for FUTURE slots
    clock.advance(61)
    cp.tick()
    assert template_replicas(cp) == 7
    cron = cp.store.get(CronFederatedHPA.KIND, "default", "nightly")
    hist = {h.rule_name: h for h in cron.status.execution_histories}
    assert hist["every-minute"].last_result == "Succeed"


def test_cron_adjusts_fhpa_min_max(env):
    cp, clock = env
    cp.store.create(CronFederatedHPA(
        metadata=ObjectMeta(name="window", namespace="default"),
        spec=CronFederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="autoscaling.karmada.io/v1alpha1",
                kind="FederatedHPA", name="web-hpa"),
            rules=[CronFederatedHPARule(
                name="biz-hours", schedule="* * * * *",
                target_min_replicas=5, target_max_replicas=20)],
        ),
    ))
    cp.tick()  # first sync registers; rules fire only for FUTURE slots
    clock.advance(61)
    cp.tick()
    h = cp.store.get(FederatedHPA.KIND, "default", "web-hpa")
    assert (h.spec.min_replicas, h.spec.max_replicas) == (5, 20)
    # min is enforced on the next HPA pass even when idle
    cp.tick()
    assert template_replicas(cp) >= 5


def test_suspended_rule_does_not_fire(env):
    cp, clock = env
    cp.store.create(CronFederatedHPA(
        metadata=ObjectMeta(name="paused", namespace="default"),
        spec=CronFederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            rules=[CronFederatedHPARule(
                name="noop", schedule="* * * * *", target_replicas=9,
                suspend=True)],
        ),
    ))
    cp.tick()  # first sync registers; rules fire only for FUTURE slots
    clock.advance(61)
    cp.tick()
    assert template_replicas(cp) == 4


def _hpa_with_metric(metric_spec, min_r=1, max_r=20):
    return FederatedHPA(
        metadata=ObjectMeta(name="web-hpa-custom", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            min_replicas=min_r, max_replicas=max_r,
            metrics=[metric_spec],
        ),
    )


def test_pods_metric_scales_on_custom_series(env):
    """Pods metric (custom.metrics.k8s.io through the adapter): the merged
    per-pod series drives replicas — desired = ceil(total / averageValue)."""
    from karmada_tpu.models.autoscaling import PodsMetricSource

    cp, clock = env
    cp.store.delete("FederatedHPA", "default", "web-hpa")
    cp.store.create(_hpa_with_metric(MetricSpec(type="Pods", pods=PodsMetricSource(
        metric="requests_per_s",
        target=MetricTarget(type="AverageValue", average_value=100),
    ))))
    # members serve 350+450=800 rps for the workload -> 8 replicas
    cp.members["m1"].custom_metrics[
        ("Deployment", "default", "web", "requests_per_s")] = 350.0
    cp.members["m2"].custom_metrics[
        ("Deployment", "default", "web", "requests_per_s")] = 450.0
    for _ in range(3):
        clock.advance(60)
        cp.tick()
    dep = cp.store.get("Deployment", "default", "web")
    assert dep.manifest["spec"]["replicas"] == 8


def test_external_metric_with_selector(env):
    """External metric: selector-filtered labeled series, AverageValue."""
    from karmada_tpu.models.autoscaling import ExternalMetricSource

    cp, clock = env
    cp.store.delete("FederatedHPA", "default", "web-hpa")
    cp.store.create(_hpa_with_metric(MetricSpec(
        type="External",
        external=ExternalMetricSource(
            metric="queue_depth", selector={"queue": "payments"},
            target=MetricTarget(type="AverageValue", average_value=10),
        ))))
    cp.metrics_provider.external["queue_depth"] = [
        {"labels": {"queue": "payments"}, "value": 60.0},
        {"labels": {"queue": "other"}, "value": 900.0},  # filtered out
    ]
    for _ in range(3):
        clock.advance(60)
        cp.tick()
    dep = cp.store.get("Deployment", "default", "web")
    assert dep.manifest["spec"]["replicas"] == 6


def test_object_metric_value_target(env):
    """Object metric with a Value target: ratio value/target scales the
    ready pod count."""
    from karmada_tpu.models.autoscaling import ObjectMetricSource

    cp, clock = env
    cp.store.delete("FederatedHPA", "default", "web-hpa")
    cp.store.create(_hpa_with_metric(MetricSpec(
        type="Object",
        object=ObjectMetricSource(
            described_object=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            metric="backlog",
            target=MetricTarget(type="Value", value=100),
        ))))
    cp.members["m1"].custom_metrics[
        ("Deployment", "default", "web", "backlog")] = 300.0
    clock.advance(60)
    cp.tick()
    dep = cp.store.get("Deployment", "default", "web")
    ready_before = 2  # initial replicas
    # ratio 3.0 over the ready pods at evaluation time
    assert dep.manifest["spec"]["replicas"] >= 2 * 3


def test_cron_rule_pushing_invalid_shape_records_failed_execution(env):
    """A CronFederatedHPA rule whose targetMinReplicas exceeds the HPA's
    maxReplicas is rejected by admission — recorded as a Failed execution,
    never a crashed controller round."""
    from karmada_tpu.models.autoscaling import (
        CronFederatedHPA,
        CronFederatedHPARule,
        CronFederatedHPASpec,
    )

    cp, clock = env
    cp.store.create(CronFederatedHPA(
        metadata=ObjectMeta(name="boom", namespace="default"),
        spec=CronFederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                "autoscaling.karmada.io/v1alpha1", "FederatedHPA", "web-hpa"),
            rules=[CronFederatedHPARule(
                name="bad", schedule="* * * * *",
                target_min_replicas=99)],  # > maxReplicas=10
        )))
    cp.tick()  # first sync registers; rules fire only for FUTURE slots
    clock.advance(61)
    cp.tick()  # must not raise
    cron = cp.store.get("CronFederatedHPA", "default", "boom")
    hist = cron.status.execution_histories[0]
    assert hist.last_result == "Failed"
    assert "admission rejected" in hist.message
