"""Cluster lease heartbeats: collector liveness vs member health.

Reference: cluster_status_controller.go:399 (initLeaseController, leases in
the karmada-cluster namespace) + the control plane's monitor grace period.
A dead COLLECTOR (not a dead member) must degrade its cluster to
Ready=Unknown, which the condition-driven taint path then acts on.
"""

from __future__ import annotations

from karmada_tpu.controllers.lease import (
    LEASE_NAMESPACE,
    ClusterLeaseMonitor,
    Lease,
    renew_cluster_lease,
)
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.cluster import COND_CLUSTER_READY, Cluster
from karmada_tpu.models.meta import get_condition
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


def test_collector_renews_lease_each_cycle():
    import time as _time

    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    lease = cp.store.get(Lease.KIND, LEASE_NAMESPACE, "m1")
    first = lease.renew_time
    _time.sleep(0.02)
    cp.tick()
    lease = cp.store.get(Lease.KIND, LEASE_NAMESPACE, "m1")
    assert lease.renew_time > first  # strictly newer: renewal really ran
    # healthy member + fresh lease: Ready stays True
    cond = get_condition(
        cp.store.get(Cluster.KIND, "", "m1").status.conditions,
        COND_CLUSTER_READY)
    assert cond.status == "True"


def test_stale_lease_degrades_to_unknown_and_taints():
    store = ObjectStore()
    runtime = Runtime()
    clock = {"now": 1000.0}
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.cluster import ClusterSpec

    store.create(Cluster(metadata=ObjectMeta(name="m1"),
                         spec=ClusterSpec()))
    renew_cluster_lease(store, "m1", clock=lambda: clock["now"])
    monitor = ClusterLeaseMonitor(store, runtime, grace_multiplier=4.0,
                                  clock=lambda: clock["now"])

    monitor.check_all()  # fresh: no degradation
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond is None

    clock["now"] += 1000.0  # far past 4 x 10s grace
    monitor.check_all()
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond is not None and cond.status == "Unknown"

    # recovery is owned by the collector: a renewed lease alone does not
    # flip Ready back (the next successful collect cycle does)
    renew_cluster_lease(store, "m1", clock=lambda: clock["now"])
    monitor.check_all()
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond.status == "Unknown"


def test_dead_collector_in_control_plane_taints_cluster():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    # simulate collector death: stop heartbeating for m1 but keep the
    # Cluster object (the member did not unjoin — its agent just died)
    del cp.cluster_status.members["m1"]
    # age the lease far past grace

    def age(lease: Lease) -> None:
        lease.renew_time -= 10_000.0
    cp.store.mutate(Lease.KIND, LEASE_NAMESPACE, "m1", age)
    cp.tick()
    cluster = cp.store.get(Cluster.KIND, "", "m1")
    cond = get_condition(cluster.status.conditions, COND_CLUSTER_READY)
    assert cond.status == "Unknown"
    from karmada_tpu.controllers.failover import TAINT_NOT_READY

    assert any(t.key == TAINT_NOT_READY for t in cluster.spec.taints)


def test_unjoin_deletes_lease():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    assert cp.store.try_get(Lease.KIND, LEASE_NAMESPACE, "m1") is not None
    cp.unjoin("m1")
    assert cp.store.try_get(Lease.KIND, LEASE_NAMESPACE, "m1") is None


def test_slow_sync_period_widens_grace():
    """A sync period longer than the lease duration must not flap healthy
    clusters to Unknown (review finding: grace follows the real cadence)."""
    store = ObjectStore()
    runtime = Runtime(periodic_interval_s=60.0)
    clock = {"now": 1000.0}
    from karmada_tpu.models.cluster import ClusterSpec
    from karmada_tpu.models.meta import ObjectMeta

    store.create(Cluster(metadata=ObjectMeta(name="m1"), spec=ClusterSpec()))
    renew_cluster_lease(store, "m1", clock=lambda: clock["now"])
    monitor = ClusterLeaseMonitor(store, runtime, grace_multiplier=4.0,
                                  clock=lambda: clock["now"])
    clock["now"] += 120.0  # stale by the 10s-lease yardstick, fresh for 60s sync
    monitor.check_all()
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond is None  # within 4 x 60s: no degradation
    clock["now"] += 200.0  # now beyond 4 x 60s
    monitor.check_all()
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond is not None and cond.status == "Unknown"
