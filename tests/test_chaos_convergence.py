"""Deterministic chaos: random member failures, policy churn, scaling, and
cordons — the control plane must keep converging.

The reference proves this class of behavior with kind-cluster E2E suites
(test/e2e/suites/base: scheduling, rescheduling, failover); here the same
storyline runs against the in-process plane with a seeded RNG, so a
regression in any controller interaction (detector x scheduler x
execution x failover x lease) surfaces as a deterministic failure.
"""

from __future__ import annotations

import random

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding


def deployment(name, replicas):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas,
                 "template": {"spec": {"containers": [
                     {"name": "c", "resources": {
                         "requests": {"cpu": "100m", "memory": "256Mi"}}}]}}},
    }


def policy(name, target):
    return PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name=name),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name=target)],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))),
    )


@pytest.mark.parametrize("seed", [5, 17])
def test_chaos_converges(seed):
    rng = random.Random(seed)
    cp = ControlPlane(backend="serial")
    for i in range(4):
        cp.add_member(f"m{i}", cpu_milli=32_000, memory_gi=128)

    apps = []
    for i in range(6):
        name = f"app-{i}"
        cp.apply(deployment(name, rng.randint(2, 8)))
        cp.apply_policy(policy(f"pol-{i}", name))
        apps.append(name)
    cp.tick()

    cordoned: set = set()
    for step in range(60):
        action = rng.randrange(5)
        if action == 0:  # member outage / recovery
            m = cp.member(f"m{rng.randrange(4)}")
            m.healthy = not m.healthy
        elif action == 1:  # scale an app
            name = rng.choice(apps)
            cp.apply(deployment(name, rng.randint(1, 12)))
        elif action == 2 and len(cordoned) < 3:  # cordon
            name = f"m{rng.randrange(4)}"
            if name not in cordoned:
                cordoned.add(name)
                from karmada_tpu.models.cluster import Taint

                cp.store.mutate(Cluster.KIND, "", name, lambda c: (
                    c.spec.taints.append(
                        Taint(key="chaos", effect="NoSchedule"))))
        elif action == 3 and cordoned:  # uncordon
            name = cordoned.pop()
            cp.store.mutate(Cluster.KIND, "", name, lambda c: (
                setattr(c.spec, "taints",
                        [t for t in c.spec.taints if t.key != "chaos"])))
        # action == 4: just tick
        cp.tick()

    # heal everything and let the plane converge
    for i in range(4):
        cp.member(f"m{i}").healthy = True
    for name in list(cordoned):
        cp.store.mutate(Cluster.KIND, "", name, lambda c: (
            setattr(c.spec, "taints",
                    [t for t in c.spec.taints if t.key != "chaos"])))
    for _ in range(8):
        cp.tick()

    # every app is fully scheduled and rendered, replica sums intact
    for name in apps:
        rb = cp.store.get(ResourceBinding.KIND, "default", f"{name}-deployment")
        want = cp.store.get("Deployment", "default", name).manifest[
            "spec"]["replicas"]
        got = sum(tc.replicas for tc in rb.spec.clusters)
        assert got == want, (name, got, want)
        # the member-side objects agree with the split
        for tc in rb.spec.clusters:
            obj = cp.member(tc.name).get("Deployment", "default", name)
            assert obj is not None, (name, tc.name)
            assert obj.manifest["spec"]["replicas"] == tc.replicas
