"""Leader election: HA scheduler replicas over one store.

Reference: every karmada binary runs controller-runtime leader election on
a coordination.k8s.io Lease so exactly one replica acts (SURVEY §5
checkpoint/resume: stateless components + leader election).
"""

from karmada_tpu.store.store import ObjectStore
from karmada_tpu.utils.leaderelection import LeaderElector, Lease


class FakeClock:
    def __init__(self, t=1_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def test_first_candidate_wins_and_renews():
    store = ObjectStore()
    clock = FakeClock()
    a = LeaderElector(store, "scheduler", "a", lease_duration_s=10, clock=clock)
    b = LeaderElector(store, "scheduler", "b", lease_duration_s=10, clock=clock)
    assert a.tick() and not b.tick()
    clock.advance(5)
    assert a.tick()  # renewal extends the lease
    clock.advance(8)
    assert not b.tick()  # still within a's renewed duration
    assert a.is_leader() and not b.is_leader()


def test_takeover_after_expiry():
    store = ObjectStore()
    clock = FakeClock()
    a = LeaderElector(store, "scheduler", "a", lease_duration_s=10, clock=clock)
    b = LeaderElector(store, "scheduler", "b", lease_duration_s=10, clock=clock)
    assert a.tick()
    clock.advance(11)  # a stopped renewing
    assert b.tick()
    assert b.is_leader()
    # a comes back: sees b's fresh lease, steps down
    assert not a.tick()
    assert not a.is_leader()


def test_graceful_release_hands_over_immediately():
    store = ObjectStore()
    clock = FakeClock()
    a = LeaderElector(store, "scheduler", "a", lease_duration_s=10, clock=clock)
    b = LeaderElector(store, "scheduler", "b", lease_duration_s=10, clock=clock)
    assert a.tick()
    a.release()
    assert b.tick()  # no expiry wait needed
    assert b.is_leader()


def test_callbacks_fire_on_transitions():
    store = ObjectStore()
    clock = FakeClock()
    events = []
    a = LeaderElector(store, "s", "a", lease_duration_s=10, clock=clock,
                      on_started_leading=lambda: events.append("a-start"),
                      on_stopped_leading=lambda: events.append("a-stop"))
    b = LeaderElector(store, "s", "b", lease_duration_s=10, clock=clock,
                      on_started_leading=lambda: events.append("b-start"))
    a.tick()
    clock.advance(11)
    b.tick()
    a.tick()
    assert events == ["a-start", "b-start", "a-stop"]


def test_standby_scheduler_takes_over_queued_work():
    """Two schedulers over one store: only the leader drains; killing its
    renewals hands the queue to the standby."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.policy import (
        REPLICA_SCHEDULING_DUPLICATED,
        ObjectMeta,
        Placement,
        PropagationPolicy,
        PropagationSpec,
        ReplicaSchedulingStrategy,
        ResourceSelector,
    )
    from karmada_tpu.models.work import ResourceBinding
    from karmada_tpu.scheduler import Scheduler
    from karmada_tpu.store.worker import Runtime

    clock = FakeClock()
    cp = ControlPlane(backend="serial", clock=clock)
    # replace the built-in always-leader scheduler with two elected replicas
    cp.scheduler.elector = LeaderElector(
        cp.store, "scheduler", "replica-1", lease_duration_s=10, clock=clock
    )
    standby_runtime = Runtime()
    standby = Scheduler(cp.store, standby_runtime, backend="serial",
                        elector=LeaderElector(cp.store, "scheduler",
                                              "replica-2", lease_duration_s=10,
                                              clock=clock))
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    standby_runtime.tick()
    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        ),
    ))
    cp.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"replicas": 2},
    })
    cp.tick()
    standby_runtime.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "web-deployment")
    assert rb.spec.clusters, "leader replica must schedule"

    # leader dies (stops renewing: only the standby runtime keeps ticking)
    cp.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web2", "namespace": "default"},
        "spec": {"replicas": 2},
    })
    # detector etc. still run (they are not elected here); the dead
    # scheduler's queue entry exists but its cycles no longer fire
    cp.scheduler.elector._leading = False  # noqa: SLF001 — simulate crash
    cp.scheduler.elector.tick = lambda: False
    clock.advance(11)
    cp.tick()
    standby_runtime.tick()
    rb2 = cp.store.get(ResourceBinding.KIND, "default", "web2-deployment")
    assert rb2.spec.clusters, "standby must take over after lease expiry"
    assert standby.elector.is_leader()
