"""Scheduling queue tier: active/backoff/unschedulable semantics
(reference pkg/scheduler/internal/queue/scheduling_queue.go) and the
service-level retry behavior they enable."""

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    ClusterAffinity,
    Placement,
    REPLICA_SCHEDULING_DUPLICATED,
    ReplicaSchedulingStrategy,
)
from karmada_tpu.models.work import (
    COND_SCHEDULED,
    ObjectReference,
    ResourceBinding,
    ResourceBindingSpec,
)
from karmada_tpu.scheduler.queue import QueuedBindingInfo, SchedulingQueue
from karmada_tpu.scheduler.service import Scheduler
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime
from karmada_tpu.utils.quantity import Quantity


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- queue unit tests --------------------------------------------------------


def test_pop_ready_priority_then_fifo():
    clk = Clock()
    q = SchedulingQueue(now=clk)
    q.push(("ns", "low-a"), priority=0)
    clk.t += 1
    q.push(("ns", "high"), priority=10)
    clk.t += 1
    q.push(("ns", "low-b"), priority=0)
    keys = [i.key for i in q.pop_ready()]
    assert keys == [("ns", "high"), ("ns", "low-a"), ("ns", "low-b")]
    assert q.depths() == {"active": 0, "backoff": 0, "unschedulable": 0}


def test_backoff_doubles_and_saturates():
    q = SchedulingQueue(initial_backoff_s=1.0, max_backoff_s=10.0)
    info = QueuedBindingInfo(key="k")
    assert q._backoff_duration(info) == 0.0
    for attempts, want in [(1, 1.0), (2, 2.0), (3, 4.0), (4, 8.0), (5, 10.0), (9, 10.0)]:
        info.attempts = attempts
        assert q._backoff_duration(info) == want


def test_backoff_flush_moves_to_active_after_expiry():
    clk = Clock()
    q = SchedulingQueue(now=clk)
    info = QueuedBindingInfo(key=("ns", "b"), attempts=2)  # 2s backoff
    q.push_backoff_if_not_present(info)
    assert q.depths()["backoff"] == 1
    assert q.flush_backoff() == 0  # not yet expired
    clk.t += 1.9
    assert q.flush_backoff() == 0
    clk.t += 0.2
    assert q.flush_backoff() == 1
    assert [i.key for i in q.pop_ready()] == [("ns", "b")]


def test_unschedulable_leftover_flush():
    clk = Clock()
    q = SchedulingQueue(now=clk, max_in_unschedulable_s=300.0)
    q.push_unschedulable_if_not_present(QueuedBindingInfo(key="u", attempts=1))
    assert q.flush_unschedulable_leftover() == 0
    clk.t += 301
    assert q.flush_unschedulable_leftover() == 1
    assert q.depths()["active"] == 1


def test_cluster_event_moves_unschedulable():
    clk = Clock()
    q = SchedulingQueue(now=clk)
    q.push_unschedulable_if_not_present(QueuedBindingInfo(key="done", attempts=0))
    backing = QueuedBindingInfo(key="backing", attempts=3)  # 4s backoff
    q.push_unschedulable_if_not_present(backing)
    clk.t += 1.0
    backing.timestamp = clk.t  # refreshed residence, still backing off
    q.move_all_to_active_or_backoff()
    d = q.depths()
    assert d["active"] == 1 and d["backoff"] == 1 and d["unschedulable"] == 0


def test_push_supersedes_backoff_and_not_present_guards():
    q = SchedulingQueue()
    info = QueuedBindingInfo(key="k", attempts=4)
    q.push_backoff_if_not_present(info)
    q.push("k", priority=1)  # external event wins over backoff
    assert q.depths()["active"] == 1 and q.depths()["backoff"] == 0
    # while active, neither failure queue accepts it
    q.push_unschedulable_if_not_present(QueuedBindingInfo(key="k"))
    q.push_backoff_if_not_present(QueuedBindingInfo(key="k"))
    assert q.depths() == {"active": 1, "backoff": 0, "unschedulable": 0}
    got = q.pop_ready()
    assert len(got) == 1 and got[0].attempts == 4  # attempts survive supersede


# -- service integration -----------------------------------------------------


def _cluster(name: str) -> Cluster:
    return Cluster(
        metadata=ObjectMeta(name=name),
        spec=ClusterSpec(),
        status=ClusterStatus(
            api_enablements=[APIEnablement("apps/v1", ["Deployment"])],
            resource_summary=ResourceSummary(
                allocatable={"cpu": Quantity.parse("64"),
                             "memory": Quantity.parse("256Gi"),
                             "pods": Quantity.parse("110")},
            ),
        ),
    )


def _binding(name: str, affinity_names, priority=None) -> ResourceBinding:
    rb = ResourceBinding()
    rb.metadata.namespace = "default"
    rb.metadata.name = name
    rb.spec = ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 namespace="default", name=name, uid=f"uid-{name}"),
        replicas=2,
        placement=Placement(
            cluster_affinity=ClusterAffinity(cluster_names=affinity_names),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED),
        ),
        schedule_priority=priority,
    )
    return rb


def test_fit_error_retries_with_backoff_without_cluster_event():
    """VERDICT r1 gap: a failed binding must retry on backoff expiry alone
    (previously only a cluster event would re-enqueue it)."""
    clk = Clock()
    store = ObjectStore()
    runtime = Runtime()
    sched = Scheduler(store, runtime, backend="serial",
                      queue=SchedulingQueue(now=clk))
    store.create(_cluster("m1"))
    store.create(_binding("app", ["absent-cluster"]))  # FitError forever
    runtime.tick()

    rb = store.get(ResourceBinding.KIND, "default", "app")
    cond = [c for c in rb.status.conditions if c.type == COND_SCHEDULED][0]
    assert cond.status == "False"
    assert sched.queue.depths()["backoff"] == 1
    info = sched.queue._info[("default", "app")]
    assert info.attempts == 1

    # no store events at all; advancing the clock past backoff retries it
    clk.t += 1.1
    runtime.tick()
    assert sched.queue._info[("default", "app")].attempts == 2
    assert sched.queue.depths()["backoff"] == 1
    # second failure backs off 2s: not retried after only 1s...
    clk.t += 1.1
    runtime.tick()
    assert sched.queue._info[("default", "app")].attempts == 2
    # ...but is after 2s
    clk.t += 1.0
    runtime.tick()
    assert sched.queue._info[("default", "app")].attempts == 3


def test_priority_order_within_batch_drain():
    clk = Clock()
    store = ObjectStore()
    runtime = Runtime()
    scheduled_order = []
    sched = Scheduler(store, runtime, backend="serial",
                      queue=SchedulingQueue(now=clk))
    orig = sched.schedule_batch

    def spy(bindings, clusters):
        scheduled_order.extend(rb.name for rb in bindings)
        return orig(bindings, clusters)

    sched.schedule_batch = spy
    store.create(_cluster("m1"))
    runtime.tick()
    scheduled_order.clear()
    # created low first; high priority must still drain first in the batch
    store.create(_binding("low", ["m1"], priority=0))
    store.create(_binding("high", ["m1"], priority=100))
    runtime.tick()
    assert scheduled_order.index("high") < scheduled_order.index("low")
    rb = store.get(ResourceBinding.KIND, "default", "high")
    assert [t.name for t in rb.spec.clusters] == ["m1"]


def test_successful_binding_forgotten():
    store = ObjectStore()
    runtime = Runtime()
    sched = Scheduler(store, runtime, backend="serial")
    store.create(_cluster("m1"))
    store.create(_binding("ok", ["m1"]))
    runtime.tick()
    assert sched.queue.depths() == {"active": 0, "backoff": 0, "unschedulable": 0}
    assert not sched.queue.has(("default", "ok"))
