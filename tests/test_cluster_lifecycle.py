"""Cluster lifecycle (join/unjoin) + rate-limited eviction.

Reference: pkg/controllers/cluster/cluster_controller.go:156-381 (finalizer
+ execution-space lifecycle), eviction_worker.go + dynamic_rate_limiter.go
(taint-driven evictions paced at ResourceEvictionRate/second; rate 0 halts).
"""

from karmada_tpu.controllers.binding import execution_namespace
from karmada_tpu.controllers.cluster import CLUSTER_FINALIZER
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding, Work


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def nginx(name="nginx", replicas=4):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    )


def test_join_adds_finalizer_and_execution_space():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1")
    cp.tick()
    cluster = cp.store.get(Cluster.KIND, "", "m1")
    assert CLUSTER_FINALIZER in cluster.metadata.finalizers
    ns = cp.store.try_get("Namespace", "", execution_namespace("m1"))
    assert ns is not None
    assert ns.metadata.labels["karmada.io/execution-space-for"] == "m1"


def test_unjoin_drains_works_then_releases_cluster():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1")
    cp.add_member("m2")
    cp.tick()
    cp.store.create(policy())
    cp.apply(nginx())
    cp.tick()
    assert len(cp.store.list(Work.KIND, execution_namespace("m1"))) >= 1
    cp.unjoin("m1")
    cp.tick()
    # execution space drained + removed; Cluster object fully gone
    assert cp.store.list(Work.KIND, execution_namespace("m1")) == []
    assert cp.store.try_get("Namespace", "", execution_namespace("m1")) is None
    assert cp.store.try_get(Cluster.KIND, "", "m1") is None
    # survivors untouched
    assert len(cp.store.list(Work.KIND, execution_namespace("m2"))) >= 1


def test_unjoin_reschedules_bindings_off_the_removed_cluster():
    """Bindings targeting the unjoined cluster lose it and the scheduler
    tops the replicas back up on survivors; no orphan Work reappears
    (regression: binding controller recreated Works in the drained space)."""
    cp = ControlPlane(backend="serial")
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.store.create(policy())
    cp.apply(nginx(replicas=4))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert {tc.name for tc in rb.spec.clusters} == {"m1", "m2"}
    cp.unjoin("m1")
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert {tc.name for tc in rb.spec.clusters} == {"m2"}
    assert sum(tc.replicas for tc in rb.spec.clusters) == 4
    # template update must not resurrect a Work for the gone cluster
    cp.apply(nginx(replicas=5))
    cp.tick()
    assert cp.store.list(Work.KIND, execution_namespace("m1")) == []


def test_eviction_rate_limits_mass_failure():
    """A zone outage with 6 affected bindings drains at the configured
    2/second instead of stampeding all six through rescheduling at once."""
    clock = FakeClock()
    cp = ControlPlane(backend="serial", clock=clock, eviction_rate=2.0,
                      default_toleration_seconds=None)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.store.create(policy())
    for i in range(6):
        cp.apply(nginx(name=f"app-{i}", replicas=2))
    cp.tick()

    def evicted_count() -> int:
        n = 0
        for rb in cp.store.list(ResourceBinding.KIND):
            if any(t.from_cluster == "m1" for t in rb.spec.graceful_eviction_tasks):
                n += 1
            elif not any(tc.name == "m1" for tc in rb.spec.clusters):
                n += 1
        return n

    cp.member("m1").healthy = False
    cp.tick()  # taints land; initial burst (max(rate,1)=2) evicts 2
    assert evicted_count() == 2, evicted_count()
    clock.advance(1.0)
    cp.tick()  # +2 tokens
    assert evicted_count() == 4
    clock.advance(1.0)
    cp.tick()
    assert evicted_count() == 6


def test_eviction_rate_zero_halts():
    clock = FakeClock()
    cp = ControlPlane(backend="serial", clock=clock, eviction_rate=0.0,
                      default_toleration_seconds=None)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.store.create(policy())
    cp.apply(nginx())
    cp.tick()
    cp.member("m1").healthy = False
    cp.tick()
    clock.advance(3600)
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert not rb.spec.graceful_eviction_tasks  # nothing evicted: halted
    assert cp.eviction_queue.pending() >= 1
