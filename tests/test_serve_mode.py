"""Threaded serve-mode soak: the control plane converges under real
concurrency (workers on threads, periodic hooks on timers).

The reference runs its unit CI under the Go race detector (Makefile:118);
the framework's equivalent evidence is this soak — every controller thread
live, concurrent template/policy churn from the test thread, convergence
asserted by polling, no reliance on the deterministic pump."""

import time

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_DIVISION_WEIGHTED,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    ClusterPreferences,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding


def deployment(name, replicas):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def wait_for(predicate, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def served_plane():
    cp = ControlPlane(backend="serial", default_toleration_seconds=None)
    cp.runtime._periodic_interval_s = 0.05  # noqa: SLF001 — fast soak ticks
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.runtime.serve()
    yield cp
    cp.runtime.stop()


def test_concurrent_churn_converges(served_plane):
    cp = served_plane
    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    ))
    # churn: create 12 workloads, rescale half of them while controllers run
    for i in range(12):
        cp.apply(deployment(f"app-{i}", 4))
    for i in range(0, 12, 2):
        cp.apply(deployment(f"app-{i}", 7))

    def all_scheduled():
        for i in range(12):
            rb = cp.store.try_get(ResourceBinding.KIND, "default",
                                  f"app-{i}-deployment")
            if rb is None:
                return False
            want = 7 if i % 2 == 0 else 4
            if rb.spec.replicas != want:
                return False
            if sum(tc.replicas for tc in rb.spec.clusters) != want:
                return False
        return True

    assert wait_for(all_scheduled), "bindings did not converge under serve mode"

    def all_applied():
        for i in range(12):
            found = any(
                cp.members[m].get("Deployment", "default", f"app-{i}") is not None
                for m in ("m1", "m2")
            )
            if not found:
                return False
        return True

    assert wait_for(all_applied), "workloads did not land in members"


def test_failover_under_serve(served_plane):
    cp = served_plane
    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    ))
    cp.apply(deployment("web", 6))

    def scheduled_on_both():
        rb = cp.store.try_get(ResourceBinding.KIND, "default", "web-deployment")
        return rb is not None and {tc.name for tc in rb.spec.clusters} == {"m1", "m2"}

    assert wait_for(scheduled_on_both)
    cp.members["m2"].healthy = False

    def drained_off_m2():
        rb = cp.store.try_get(ResourceBinding.KIND, "default", "web-deployment")
        if rb is None:
            return False
        on_m2 = any(tc.name == "m2" for tc in rb.spec.clusters)
        evicting = any(t.from_cluster == "m2"
                       for t in rb.spec.graceful_eviction_tasks)
        total = sum(tc.replicas for tc in rb.spec.clusters
                    if tc.name != "m2")
        return (not on_m2 or evicting) and total == 6

    assert wait_for(drained_off_m2, timeout=30.0), (
        "failover did not drain the dead member under serve mode"
    )
