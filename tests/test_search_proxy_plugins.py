"""Search-proxy plugin chain + networked external search sink.

Reference: pkg/search/proxy/framework (ordered chain of responsibility,
one plugin handles each request) and pkg/search/backendstore/
opensearch.go:127-193 (the offboard network-protocol sink).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from karmada_tpu.models.search import BackendStoreConfig
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.search.backend import make_backend
from karmada_tpu.search.fts import SqliteFTSBackend
from karmada_tpu.search.proxyframework import (
    ProxyPlugin,
    ProxyPluginRegistry,
    ProxyRequest,
    default_registry,
)
from karmada_tpu.search.remote import RemoteTcpBackend, serve_backend


class _Recorder(ProxyPlugin):
    def __init__(self, name, order, supports=True, payload=None):
        self.name, self.order = name, order
        self.supports = supports
        self.payload = payload if payload is not None else {"by": name}
        self.connects = 0

    def support(self, req):
        return self.supports

    def connect(self, req):
        def handler():
            self.connects += 1
            return 200, self.payload
        return handler


def test_smallest_order_supporting_plugin_wins():
    reg = ProxyPluginRegistry()
    reg.register(_Recorder("late", 300))
    reg.register(_Recorder("early", 10))
    reg.register(_Recorder("never", 1, supports=False))
    code, payload = reg.route(ProxyRequest(verb="get", kind="X"))()
    assert (code, payload) == (200, {"by": "early"})


def test_enablement_spec_reorders_the_chain():
    reg = ProxyPluginRegistry()
    a, b = _Recorder("A", 1), _Recorder("B", 2)
    reg.register(a)
    reg.register(b)
    reg.set_enablement("*,-A")  # disable A: B now sees the request first
    assert reg.route(ProxyRequest(verb="get"))()[1] == {"by": "B"}
    reg.set_enablement("A")  # bare allowlist: only A runs
    assert reg.route(ProxyRequest(verb="get"))()[1] == {"by": "A"}
    reg.set_enablement("-A,-B")
    assert reg.route(ProxyRequest(verb="get")) is None


def test_chain_exhaustion_returns_none():
    reg = ProxyPluginRegistry()
    reg.register(_Recorder("only", 1, supports=False))
    assert reg.route(ProxyRequest(verb="get", kind="X")) is None


# -- the in-tree chain over a live plane ------------------------------------

from tests.test_query_plane import cp, deployment, dup_policy, registry  # noqa: F401,E402
from karmada_tpu.search.httpapi import QueryPlaneServer  # noqa: E402


@pytest.fixture
def served(cp):  # noqa: F811 — pytest fixture chaining
    cp.store.create(registry())
    cp.apply_policy(dup_policy())
    cp.apply(deployment("web"))
    cp.tick()
    srv = QueryPlaneServer(cp.store, cp.members, cp.cluster_proxy,
                           search_cache=cp.search_cache,
                           metrics_provider=cp.metrics_provider)
    url = srv.start()
    yield cp, srv, url
    srv.stop()


def get_json(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read())


def test_cached_kind_served_by_cache_plugin(served):
    cp, srv, url = served
    out = get_json(url, "/search/cache/Deployment")
    assert out and out[0]["metadata"]["name"] == "web"


def test_uncached_kind_falls_through_to_karmada_plugin(served):
    """The reference karmada plugin serves whatever no cache/cluster plugin
    claimed — here, a control-plane kind no registry selects."""
    cp, srv, url = served
    assert not cp.search_cache.has_kind("PropagationPolicy")
    out = get_json(url, "/search/cache/PropagationPolicy")
    assert out and out[0]["metadata"]["name"] == "pp"


def test_out_of_tree_plugin_interposes_by_order(served):
    cp, srv, url = served
    intercept = _Recorder("Interpose", -10, payload={"intercepted": True})
    srv.proxy_plugins.register(intercept)
    try:
        out = get_json(url, "/search/cache/Deployment")
        assert out == {"intercepted": True}
        assert intercept.connects == 1
        # disable it: the cache plugin is first again
        srv.proxy_plugins.set_enablement("*,-Interpose")
        out = get_json(url, "/search/cache/Deployment")
        assert isinstance(out, list) and out[0]["metadata"]["name"] == "web"
    finally:
        srv.proxy_plugins.unregister(intercept.name)
        srv.proxy_plugins.set_enablement("*")


def test_member_scoped_reads_ride_the_cluster_plugin(served):
    cp, srv, url = served
    # replace the chain with JUST the cluster plugin: the member read must
    # still work, proving it is the plugin serving this route
    srv.proxy_plugins.set_enablement("Cluster")
    try:
        one = get_json(url, "/clusters/m1/proxy/Deployment/default/web")
        assert one["metadata"]["name"] == "web"
        listed = get_json(url, "/clusters/m1/proxy/Deployment")
        assert any(m["metadata"]["name"] == "web" for m in listed)
    finally:
        srv.proxy_plugins.set_enablement("*")


# -- networked sink across a real socket ------------------------------------


def _obj(name, kind="ConfigMap", payload="tpu solver"):
    return Unstructured.from_manifest({
        "apiVersion": "v1", "kind": kind,
        "metadata": {"name": name, "namespace": "default"},
        "data": {"note": payload},
    })


def test_remote_sink_upsert_query_delete_across_socket():
    sink = SqliteFTSBackend(":memory:")
    server = serve_backend(sink)
    host, port = server.server_address
    try:
        backend = make_backend(BackendStoreConfig(
            kind="RemoteTCP", addresses=[f"{host}:{port}"]))
        assert isinstance(backend, RemoteTcpBackend)
        backend.upsert("m1", _obj("alpha"))
        backend.upsert("m2", _obj("beta", payload="other text"))
        assert backend.count() == 2
        hits = backend.query("solver")
        assert [h["name"] for h in hits] == ["alpha"]
        hits = backend.query("text", cluster="m2")
        assert [h["name"] for h in hits] == ["beta"]
        backend.delete("m1", _obj("alpha"))
        assert backend.count() == 1
        backend.close()
    finally:
        server.shutdown()


def test_remote_sink_unreachable_address_fails_loudly():
    with pytest.raises(ConnectionError):
        RemoteTcpBackend(["127.0.0.1:1"], timeout=0.3)


def test_remote_sink_tries_addresses_in_order():
    sink = SqliteFTSBackend(":memory:")
    server = serve_backend(sink)
    host, port = server.server_address
    try:
        backend = RemoteTcpBackend(["127.0.0.1:1", f"{host}:{port}"],
                                   timeout=0.5)
        backend.upsert("m1", _obj("gamma"))
        assert backend.count() == 1
        backend.close()
    finally:
        server.shutdown()


def test_cache_drives_remote_sink_end_to_end(cp):  # noqa: F811
    """A ResourceRegistry pointing at a RemoteTCP sink: cached member
    objects stream across the socket into the remote engine, and the query
    plane's /search/query surface reaches it via backend_of."""
    sink = SqliteFTSBackend(":memory:")
    server = serve_backend(sink)
    host, port = server.server_address
    try:
        reg = registry()
        reg.spec.backend_store = BackendStoreConfig(
            kind="RemoteTCP", addresses=[f"{host}:{port}"])
        cp.store.create(reg)
        cp.apply_policy(dup_policy())
        cp.apply(deployment("web"))
        cp.tick()
        assert sink.count() >= 1  # the sink lives on the SERVER side
        backend = cp.search_cache.backend_of(reg.metadata.name)
        hits = backend.query("web")
        assert any(h["name"] == "web" for h in hits)
    finally:
        server.shutdown()
