"""End-to-end tests of the serial schedule() pipeline: filters + scores +
general-estimator capacity + selection + assignment (reference call stack 3.2)."""

import pytest

from karmada_tpu.estimator import GeneralEstimator
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
    Taint,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
    Toleration,
)
from karmada_tpu.models.work import (
    GracefulEvictionTask,
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import serial
from karmada_tpu.utils.quantity import parse_quantity


def make_cluster(
    name,
    cpu="100",
    memory="1000Gi",
    pods="1000",
    region="",
    zone="",
    provider="",
    taints=(),
    labels=None,
    allocated_cpu="0",
):
    summary = ResourceSummary(
        allocatable={
            "cpu": parse_quantity(cpu),
            "memory": parse_quantity(memory),
            "pods": parse_quantity(pods),
        },
        allocated={"cpu": parse_quantity(allocated_cpu)},
    )
    return Cluster(
        metadata=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=ClusterSpec(
            region=region,
            zone=zone,
            zones=[zone] if zone else [],
            provider=provider,
            taints=list(taints),
        ),
        status=ClusterStatus(
            api_enablements=[
                APIEnablement(group_version="apps/v1", resources=["Deployment"])
            ],
            resource_summary=summary,
        ),
    )


def deployment_spec(replicas, cpu="1", placement=None):
    return ResourceBindingSpec(
        resource=ObjectReference(
            api_version="apps/v1", kind="Deployment", namespace="default",
            name="web", uid="uid-1",
        ),
        replicas=replicas,
        replica_requirements=ReplicaRequirements(
            resource_request={"cpu": parse_quantity(cpu)}
        ),
        placement=placement or Placement(),
    )


def schedule(spec, clusters, status=None):
    cal = serial.make_cal_available([GeneralEstimator()])
    return serial.schedule(spec, status or ResourceBindingStatus(), clusters, cal)


def as_map(result):
    return {tc.name: tc.replicas for tc in result}


def test_duplicated_all_clusters():
    clusters = [make_cluster("m1"), make_cluster("m2"), make_cluster("m3")]
    spec = deployment_spec(3)
    got = schedule(spec, clusters)
    assert as_map(got) == {"m1": 3, "m2": 3, "m3": 3}


def test_api_enablement_filters():
    c_bad = make_cluster("m2")
    c_bad.status.api_enablements = []
    clusters = [make_cluster("m1"), c_bad]
    got = schedule(deployment_spec(2), clusters)
    assert as_map(got) == {"m1": 2}


def test_taints_filter_and_toleration():
    tainted = make_cluster("m2", taints=[Taint(key="k", value="v", effect="NoSchedule")])
    clusters = [make_cluster("m1"), tainted]
    got = schedule(deployment_spec(1), clusters)
    assert as_map(got) == {"m1": 1}

    placement = Placement(
        cluster_tolerations=[Toleration(key="k", operator="Equal", value="v")]
    )
    got = schedule(deployment_spec(1, placement=placement), clusters)
    assert as_map(got) == {"m1": 1, "m2": 1}


def test_cluster_affinity_label_selector():
    from karmada_tpu.models.meta import LabelSelector

    clusters = [
        make_cluster("m1", labels={"tier": "prod"}),
        make_cluster("m2", labels={"tier": "dev"}),
    ]
    placement = Placement(
        cluster_affinity=ClusterAffinity(
            label_selector=LabelSelector(match_labels={"tier": "prod"})
        )
    )
    got = schedule(deployment_spec(2, placement=placement), clusters)
    assert as_map(got) == {"m1": 2}


def test_cluster_affinity_exclude():
    clusters = [make_cluster("m1"), make_cluster("m2")]
    placement = Placement(cluster_affinity=ClusterAffinity(exclude_clusters=["m1"]))
    got = schedule(deployment_spec(2, placement=placement), clusters)
    assert as_map(got) == {"m2": 2}


def test_eviction_filter():
    clusters = [make_cluster("m1"), make_cluster("m2")]
    spec = deployment_spec(2)
    spec.graceful_eviction_tasks = [GracefulEvictionTask(from_cluster="m1")]
    got = schedule(spec, clusters)
    assert as_map(got) == {"m2": 2}


def test_no_feasible_cluster_raises_fit_error():
    clusters = [make_cluster("m1", taints=[Taint(key="k", effect="NoSchedule")])]
    with pytest.raises(serial.FitError):
        schedule(deployment_spec(1), clusters)


def test_dynamic_weight_capacity_division():
    # capacity cpu: m1=30, m2=60 -> dynamic weights 30:60 for 9 replicas
    clusters = [make_cluster("m1", cpu="30"), make_cluster("m2", cpu="60")]
    placement = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Weighted",
            weight_preference=ClusterPreferences(dynamic_weight="AvailableReplicas"),
        )
    )
    got = schedule(deployment_spec(9, cpu="1", placement=placement), clusters)
    assert as_map(got) == {"m1": 3, "m2": 6}


def test_aggregated_prefers_fewest_clusters():
    clusters = [make_cluster("m1", cpu="100"), make_cluster("m2", cpu="10")]
    placement = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Aggregated",
        )
    )
    got = schedule(deployment_spec(50, cpu="1", placement=placement), clusters)
    assert as_map(got) == {"m1": 50}


def test_allocated_reduces_capacity():
    clusters = [make_cluster("m1", cpu="10", allocated_cpu="8")]
    placement = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Aggregated",
        )
    )
    got = schedule(deployment_spec(2, cpu="1", placement=placement), clusters)
    assert as_map(got) == {"m1": 2}
    with pytest.raises(serial.UnschedulableError):
        schedule(deployment_spec(3, cpu="1", placement=placement), clusters)


def test_spread_by_region_ha():
    clusters = [
        make_cluster("a1", region="r1"),
        make_cluster("a2", region="r1"),
        make_cluster("b1", region="r2"),
        make_cluster("c1", region=""),  # filtered: no region property
    ]
    placement = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field="region", min_groups=2, max_groups=2),
            SpreadConstraint(spread_by_field="cluster", min_groups=2, max_groups=2),
        ]
    )
    got = schedule(deployment_spec(1, placement=placement), clusters)
    assert len(got) == 2
    names = set(as_map(got))
    assert "b1" in names  # one cluster from each region
    assert names & {"a1", "a2"}


def test_scale_up_prefers_scheduled_clusters():
    # steady scale-up: previously scheduled clusters keep their replicas
    clusters = [make_cluster("m1", cpu="100"), make_cluster("m2", cpu="100")]
    placement = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Aggregated",
        )
    )
    spec = deployment_spec(10, cpu="1", placement=placement)
    first = schedule(spec, clusters)
    spec.clusters = first
    spec.replicas = 20
    second = schedule(spec, clusters)
    m = as_map(second)
    assert sum(m.values()) == 20
    for name, r in as_map(first).items():
        assert m.get(name, 0) >= r  # no disruption on scale-up


def test_cal_available_clamps_unauthentic_to_spec_replicas():
    """core/util.go:104-109: clusters no estimator authenticated keep
    spec.replicas, not MaxInt32, so Aggregated ordering matches the
    reference."""
    from karmada_tpu.models.work import TargetCluster

    class HalfBlind:
        def max_available_replicas(self, clusters, requirements):
            # authenticates only the first cluster
            out = [TargetCluster(name=c.name, replicas=-1) for c in clusters]
            out[0].replicas = 7
            return out

    from karmada_tpu.models.cluster import Cluster
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.work import ObjectReference, ResourceBindingSpec
    from karmada_tpu.ops.serial import make_cal_available

    clusters = [Cluster(metadata=ObjectMeta(name=n)) for n in ("a", "b")]
    spec = ResourceBindingSpec(resource=ObjectReference(uid="u"), replicas=12)
    cal = make_cal_available([HalfBlind()])
    got = {tc.name: tc.replicas for tc in cal(clusters, spec)}
    assert got == {"a": 7, "b": 12}
