"""Chaos plane (karmada_tpu/chaos): deterministic fault injection,
estimator circuit breaking, recoverable backend degrade, and the
post-soak safety auditor.

Covers the ISSUE-8 acceptance surface: the fault-spec grammar, per-seam
injection semantics (estimator RPC, device dispatch/d2h, device cycle,
resident mirrors, watch bus, worker reconcile, lease heartbeat), the
typed estimator error taxonomy + bounded full-jitter retry + per-cluster
circuit breaker, cycle fault containment (no binding lost), the
degrade/cooldown/re-arm path, the /debug/chaos endpoint, the disarmed
compile-cache check, and the compressed chaos soak with zero safety
violations.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from karmada_tpu import chaos
from karmada_tpu.estimator.client import (
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    AccurateEstimatorClient,
    CircuitBreaker,
    ESTIMATOR_ERRORS,
    ESTIMATOR_RETRIES,
)
from karmada_tpu.estimator.wire import (
    LocalTransport,
    Transport,
    UNAUTHENTIC_REPLICA,
)
from karmada_tpu.loadgen import (
    LoadDriver,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    get_scenario,
    warm_device_path,
)
from karmada_tpu.loadgen.driver import LOADGEN_NS, build_binding, build_cluster
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.scheduler import metrics as sched_metrics
from karmada_tpu.scheduler.queue import SchedulingQueue
from karmada_tpu.scheduler.service import Scheduler
from karmada_tpu.store.store import ADDED, Event, ObjectStore, WatchBus
from karmada_tpu.store.worker import (
    AsyncWorker,
    RECONCILE_ERRORS,
    Runtime,
)


@pytest.fixture(autouse=True)
def _always_disarm():
    """No test may leak an armed chaos plane into the rest of the suite."""
    yield
    chaos.disarm()


# ---------------------------------------------------------------------------
# spec grammar + plane mechanics
# ---------------------------------------------------------------------------


def test_spec_grammar_round_trip():
    rules = chaos.parse_spec(
        "estimator.rpc:error@0.25;device.cycle:hang:2.5#1,"
        "store.watch:drop#3")
    assert [(r.site, r.mode, r.arg, r.prob, r.count) for r in rules] == [
        ("estimator.rpc", "error", None, 0.25, None),
        ("device.cycle", "hang", 2.5, 1.0, 1),
        ("store.watch", "drop", None, 1.0, 3),
    ]


@pytest.mark.parametrize("bad", [
    "nope.site:raise",            # unknown site
    "estimator.rpc:explode",      # unknown mode for the site
    "estimator.rpc",              # no mode
    "estimator.rpc:error@2.0",    # probability out of range
    "estimator.rpc:error#x",      # bad count
    "device.cycle:hang:abc",      # non-numeric arg
])
def test_spec_grammar_rejects(bad):
    with pytest.raises(ValueError):
        chaos.parse_spec(bad)


def test_fire_budget_and_state_payload():
    plane = chaos.configure("worker.reconcile:error#2", seed=7)
    assert chaos.armed()
    assert chaos.fire("worker.reconcile") is not None
    assert chaos.fire("worker.reconcile") is not None
    assert chaos.fire("worker.reconcile") is None  # budget spent
    assert chaos.fire("estimator.rpc") is None     # no rule for the site
    state = chaos.state_payload()
    assert state["enabled"] and state["fired_total"] == 2
    assert state["rules"][0]["fired"] == 2
    assert plane.unspent_rules() == []
    chaos.disarm()
    assert not chaos.armed()
    assert chaos.state_payload() == {"enabled": False}


def test_probability_draws_are_seed_deterministic():
    def draws(seed):
        chaos.configure("store.watch:drop@0.5", seed=seed)
        out = [chaos.fire("store.watch") is not None for _ in range(64)]
        chaos.disarm()
        return out

    a, b, c = draws(3), draws(3), draws(4)
    assert a == b, "same seed + call sequence must fire identically"
    assert a != c, "a different seed must produce a different sequence"
    assert 8 < sum(a) < 56  # the draw really is probabilistic


def test_clear_closes_a_fault_window():
    plane = chaos.configure("estimator.rpc:error;store.watch:drop")
    assert plane.clear("estimator.rpc") == 1
    assert chaos.fire("estimator.rpc") is None
    assert chaos.fire("store.watch") is not None
    assert plane.clear(None) == 1
    assert chaos.fire("store.watch") is None


# ---------------------------------------------------------------------------
# estimator: typed classification, retry, circuit breaker
# ---------------------------------------------------------------------------


class _FlakyTransport(Transport):
    """Raises (or returns) a scripted sequence, then answers cleanly."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def call(self, method, request):
        self.calls += 1
        if self.script:
            item = self.script.pop(0)
            if isinstance(item, BaseException):
                raise item
            return item
        return {"maxReplicas": 7, "unschedulableReplicas": 0}


def _client(**kw):
    kw.setdefault("sleep", lambda _s: None)
    kw.setdefault("retry_attempts", 3)
    return AccurateEstimatorClient(**kw)


def _err(kind):
    return ESTIMATOR_ERRORS.value(kind=kind)


def test_typed_classification_and_retry():
    client = _client()
    t = _FlakyTransport([ConnectionError("boom"), TimeoutError("slow"),
                         {"unschedulableReplicas": "garbage"}])
    client.register("c1", t)
    base = {k: _err(k) for k in ("unreachable", "timeout", "malformed")}
    r0 = ESTIMATOR_RETRIES.value(method="GetUnschedulableReplicas")
    # 3 attempts: unreachable, timeout, malformed -> call fails typed
    assert client.unschedulable_replicas(
        "c1", "Deployment", "ns", "x") == UNAUTHENTIC_REPLICA
    assert _err("unreachable") == base["unreachable"] + 1
    assert _err("timeout") == base["timeout"] + 1
    assert _err("malformed") == base["malformed"] + 1
    assert ESTIMATOR_RETRIES.value(
        method="GetUnschedulableReplicas") == r0 + 2
    # next call: clean answer, breaker stays closed
    assert client.unschedulable_replicas("c1", "Deployment", "ns", "x") == 0
    assert client.breaker.state("c1") == CIRCUIT_CLOSED


def test_retry_recovers_transient_failure_within_one_call():
    client = _client()
    client.register("c1", _FlakyTransport([ConnectionError("blip")]))
    assert client.unschedulable_replicas("c1", "Deployment", "ns", "x") == 0
    assert client.breaker.state("c1") == CIRCUIT_CLOSED


def test_full_jitter_backoff_is_bounded_and_deterministic():
    slept = []
    client = _client(sleep=slept.append, retry_attempts=4,
                     retry_base_s=0.1, retry_cap_s=0.15)
    client.register("c1", _FlakyTransport([ConnectionError()] * 4))
    client.unschedulable_replicas("c1", "Deployment", "ns", "x")
    assert len(slept) == 3
    for k, s in enumerate(slept):
        assert 0.0 <= s <= min(0.15, 0.1 * (2 ** k))


def test_circuit_breaker_lifecycle_on_injected_clock():
    clock = {"now": 0.0}
    br = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0,
                        clock=lambda: clock["now"])
    for _ in range(3):
        assert br.allow("c1")
        br.record_failure("c1")
    assert br.state("c1") == CIRCUIT_OPEN
    assert not br.allow("c1")  # open: short-circuit
    clock["now"] += 10.0
    assert br.allow("c1")      # half-open probe
    assert br.state("c1") == CIRCUIT_HALF_OPEN
    assert not br.allow("c1")  # only ONE probe flies
    br.record_failure("c1")    # failed probe re-opens for a full window
    assert br.state("c1") == CIRCUIT_OPEN
    clock["now"] += 9.9
    assert not br.allow("c1")
    clock["now"] += 0.2
    assert br.allow("c1")
    br.record_success("c1")
    assert br.state("c1") == CIRCUIT_CLOSED
    tos = [t["to"] for t in br.transition_log()]
    assert tos == ["open", "half-open", "open", "half-open", "closed"]


def test_open_circuit_short_circuits_the_wire():
    clock = {"now": 0.0}
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=100.0,
                        clock=lambda: clock["now"])
    client = _client(breaker=br, retry_attempts=1)
    t = _FlakyTransport([ConnectionError(), ConnectionError()])
    client.register("c1", t)
    for _ in range(2):
        client.unschedulable_replicas("c1", "Deployment", "ns", "x")
    assert br.state("c1") == CIRCUIT_OPEN
    calls_before = t.calls
    base = _err("circuit_open")
    assert client.unschedulable_replicas(
        "c1", "Deployment", "ns", "x") == UNAUTHENTIC_REPLICA
    assert t.calls == calls_before, "open circuit must not touch the wire"
    assert _err("circuit_open") == base + 1


def test_chaos_estimator_modes():
    chaos.configure("estimator.rpc:garbage#1")
    client = _client(retry_attempts=1)
    client.register("c1", LocalTransport(
        lambda m, r: {"unschedulableReplicas": 4}))
    base = _err("malformed")
    assert client.unschedulable_replicas(
        "c1", "Deployment", "ns", "x") == UNAUTHENTIC_REPLICA
    assert _err("malformed") == base + 1
    # budget spent: the seam is transparent again
    assert client.unschedulable_replicas("c1", "Deployment", "ns", "x") == 4
    chaos.disarm()
    chaos.configure("estimator.rpc:slow:0.0")
    assert client.unschedulable_replicas("c1", "Deployment", "ns", "x") == 4


# ---------------------------------------------------------------------------
# watch bus, worker, lease seams
# ---------------------------------------------------------------------------


def _event(name="x"):
    return Event(type=ADDED, obj=build_binding(name))


def test_watch_bus_drop_dup_stall_reorder():
    bus = WatchBus()
    seen = []
    bus.subscribe(lambda e: seen.append(e.obj.metadata.name))

    chaos.configure("store.watch:drop#1")
    bus.publish(_event("dropped"))
    bus.publish(_event("a"))
    assert seen == ["a"]

    chaos.disarm()
    chaos.configure("store.watch:dup#1")
    bus.publish(_event("b"))
    assert seen == ["a", "b", "b"]

    chaos.disarm()
    chaos.configure("store.watch:stall#1")
    bus.publish(_event("held"))
    assert seen == ["a", "b", "b"]  # held back
    bus.publish(_event("c"))
    # stall: delivered BEFORE the next event (delayed, order kept)
    assert seen == ["a", "b", "b", "held", "c"]

    chaos.disarm()
    chaos.configure("store.watch:reorder#1")
    bus.publish(_event("late"))
    bus.publish(_event("d"))
    # reorder: delivered AFTER the next event (order inverted)
    assert seen[-2:] == ["d", "late"]


def test_watch_bus_flush_held_delivers_stragglers():
    bus = WatchBus()
    seen = []
    bus.subscribe(lambda e: seen.append(e.obj.metadata.name))
    chaos.configure("store.watch:stall#1")
    bus.publish(_event("straggler"))
    assert seen == []
    assert bus.flush_held() == 1
    assert seen == ["straggler"]
    assert bus.flush_held() == 0


def test_worker_reconcile_fault_takes_the_retry_path():
    done = []
    w = AsyncWorker("chaos-test", lambda key: done.append(key))
    chaos.configure("worker.reconcile:error#1")
    base = RECONCILE_ERRORS.value(worker="chaos-test")
    w.enqueue("k")
    assert w.process_one()
    assert done == [] and w.pending() == 1  # raised -> requeued
    assert RECONCILE_ERRORS.value(worker="chaos-test") == base + 1
    assert w.process_one()
    assert done == ["k"]  # budget spent: the retry succeeds


def test_lease_heartbeat_drop_ages_out_to_unknown():
    from karmada_tpu.controllers.lease import (
        LEASE_NAMESPACE,
        ClusterLeaseMonitor,
        Lease,
        renew_cluster_lease,
    )
    from karmada_tpu.models.cluster import COND_CLUSTER_READY, ClusterSpec
    from karmada_tpu.models.meta import ObjectMeta, get_condition

    store = ObjectStore()
    clock = {"now": 1000.0}
    store.create(Cluster(metadata=ObjectMeta(name="m1"), spec=ClusterSpec()))
    renew_cluster_lease(store, "m1", clock=lambda: clock["now"])
    monitor = ClusterLeaseMonitor(store, Runtime(),
                                  clock=lambda: clock["now"])
    chaos.configure("lease.heartbeat:drop")
    clock["now"] += 20.0
    renew_cluster_lease(store, "m1", clock=lambda: clock["now"])  # dropped
    lease = store.get(Lease.KIND, LEASE_NAMESPACE, "m1")
    assert lease.renew_time == 1000.0, "the heartbeat must be suppressed"
    clock["now"] += 100.0  # past 4 x 10s grace since the last REAL renewal
    monitor.check_all()
    cond = get_condition(store.get(Cluster.KIND, "", "m1").status.conditions,
                         COND_CLUSTER_READY)
    assert cond is not None and cond.status == "Unknown"


# ---------------------------------------------------------------------------
# scheduler: cycle fault containment, degrade + re-arm
# ---------------------------------------------------------------------------


def _slice(backend="device", **kw):
    store = ObjectStore()
    rt = Runtime()
    sched = Scheduler(store, rt, backend=backend,
                      queue=SchedulingQueue(initial_backoff_s=0.0), **kw)
    for i in range(2):
        store.create(build_cluster(f"cf-m{i}"))
    return store, rt, sched


def _all_scheduled(store):
    rbs = list(store.list(ResourceBinding.KIND))
    return rbs and all(rb.spec.clusters for rb in rbs)


def test_cycle_fault_containment_no_binding_lost():
    """A dispatch-time device fault fails the whole cycle; the popped
    bindings must re-queue (backoff) and schedule on the retry instead
    of vanishing until a cluster event rescans the store."""
    store, rt, sched = _slice()
    for i in range(3):
        store.create(build_binding(f"cf-b{i}"))
    chaos.configure("device.dispatch:raise#1")
    base = sched_metrics.CYCLE_FAULTS.value(kind="ChaosFault")
    rt.pump()          # the faulted cycle: contained, bindings -> backoff
    rt.tick()          # flush_backoff (expiry 0) + the retry cycle
    assert _all_scheduled(store)
    assert sched_metrics.CYCLE_FAULTS.value(kind="ChaosFault") == base + 1


def test_d2h_poison_surfaces_as_invariant_violation():
    """A poisoned COO plane must fail LOUDLY through the d2h guard —
    never decode into a wrong placement — and the cycle retries."""
    store, rt, sched = _slice()
    store.create(build_binding("poison-b0"))
    chaos.configure("device.d2h:poison#1")
    base = sched_metrics.CYCLE_FAULTS.value(kind="InvariantViolation")
    rt.pump()
    rt.tick()
    assert _all_scheduled(store)
    assert sched_metrics.CYCLE_FAULTS.value(
        kind="InvariantViolation") == base + 1


def test_degrade_then_cooldown_rearm():
    store, rt, sched = _slice(device_cycle_timeout_s=None,
                              device_recover_cycles=1)
    store.create(build_binding("dg-warm"))
    rt.pump()  # unguarded: pays the jit compile
    sched.device_cycle_timeout_s = 0.5
    chaos.configure("device.cycle:hang:1.5#1")
    d0 = sched_metrics.BACKEND_DEGRADED.total()
    r0 = sched_metrics.BACKEND_REARMED.value(backend="device")
    store.create(build_binding("dg-b1"))
    rt.pump()
    rt.tick()
    assert sched_metrics.BACKEND_DEGRADED.total() == d0 + 1
    assert sched._degraded_from == "device"  # noqa: SLF001
    # the abandoned batch re-entered through the degraded host backend
    assert _all_scheduled(store)
    # next cycle satisfies the 1-cycle cooldown: the plane re-arms device
    store.create(build_binding("dg-b2"))
    rt.pump()
    rt.tick()
    assert sched.backend == "device"
    assert sched_metrics.BACKEND_REARMED.value(backend="device") == r0 + 1
    assert _all_scheduled(store)
    # give the abandoned zombie its sleep back before the test ends
    time.sleep(1.2)


def test_one_way_degrade_without_recover_cycles():
    store, rt, sched = _slice(device_cycle_timeout_s=None,
                              device_recover_cycles=None)
    store.create(build_binding("ow-warm"))
    rt.pump()
    sched.device_cycle_timeout_s = 0.5
    chaos.configure("device.cycle:hang:1.5#1")
    store.create(build_binding("ow-b1"))
    rt.pump()
    rt.tick()
    degraded_to = sched.backend
    assert degraded_to != "device"
    for i in range(3):
        store.create(build_binding(f"ow-b{i + 2}"))
        rt.pump()
    assert sched.backend == degraded_to, "legacy degrade stays one-way"
    time.sleep(1.2)


# ---------------------------------------------------------------------------
# resident corruption: auditable rebuild, never a wrong placement
# ---------------------------------------------------------------------------


def test_resident_corrupt_forces_bit_exact_rebuild():
    from karmada_tpu.ops import tensors
    from karmada_tpu.resident import ResidentState, RowToken
    from karmada_tpu.resident.state import RESIDENT_AUDITS, compare_batches

    clusters = [build_cluster(f"rc-m{i}") for i in range(3)]
    bindings = [build_binding(f"rc-b{i}") for i in range(4)]
    items = [(rb.spec, rb.status) for rb in bindings]
    tokens = [RowToken(f"{LOADGEN_NS}/rc-b{i}", 1) for i in range(4)]
    state = ResidentState(device_plane=False, audit_interval=0)
    state.begin_cycle(clusters)
    state.encode_cycle(items, tokens)  # adopt
    state.begin_cycle(clusters)
    chaos.configure("resident.mirror:corrupt#1")
    m0 = RESIDENT_AUDITS.value(outcome="mismatch")
    served = state.encode_cycle(items, tokens)
    assert RESIDENT_AUDITS.value(outcome="mismatch") == m0 + 1
    # the served batch is the FRESH encode, bit-exact — the corruption
    # never reached a solve
    fresh = tensors.encode_batch(items, state.cindex, state.estimator)
    assert compare_batches(served, fresh) == []
    stats = state.stats()
    assert stats["audits"]["mismatch"] == 1
    assert stats["rebuilds"].get("audit-mismatch") == 1
    # the plane re-adopted and keeps serving
    state.begin_cycle(clusters)
    again = state.encode_cycle(items, tokens)
    assert compare_batches(again, fresh) == []


# ---------------------------------------------------------------------------
# disarmed cost: no new jit compiles, seams inert
# ---------------------------------------------------------------------------


def test_disarmed_chaos_compiles_nothing_new():
    """Compile-cache counter check (the explain plane's pattern): the
    chaos seams are host-side only, so arming/disarming the plane must
    never add a jit variant or recompile the disarmed signature."""
    from karmada_tpu.ops import tensors
    from karmada_tpu.ops.solver import _jit_cache_size, solve_compact

    clusters = [build_cluster(f"cc-m{i}") for i in range(3)]
    items = [(rb.spec, rb.status)
             for rb in (build_binding(f"cc-b{i}") for i in range(2))]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex)
    solve_compact(batch, waves=1)
    c0 = _jit_cache_size()
    if c0 is None:
        pytest.skip("jit cache size not exposed on this jax")
    assert not chaos.armed()
    solve_compact(batch, waves=1)
    assert _jit_cache_size() == c0, "disarmed re-run must not recompile"
    # even ARMED the seams are pure host work around the same programs
    chaos.configure("device.d2h:poison#0")  # armed, zero budget
    solve_compact(batch, waves=1)
    assert _jit_cache_size() == c0, "the chaos plane must never touch jit"


# ---------------------------------------------------------------------------
# /debug/chaos
# ---------------------------------------------------------------------------


def test_debug_chaos_endpoint():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    srv = ObservabilityServer()
    url = srv.start()
    try:
        with urllib.request.urlopen(url + "/debug/chaos", timeout=5) as r:
            assert json.loads(r.read()) == {"enabled": False}
        chaos.configure("worker.reconcile:error#1", seed=3)
        chaos.fire("worker.reconcile", worker="w")
        with urllib.request.urlopen(url + "/debug/chaos", timeout=5) as r:
            state = json.loads(r.read())
        assert state["enabled"] and state["seed"] == 3
        assert state["fired_by_site"] == {"worker.reconcile": 1}
        assert state["recent"][0]["site"] == "worker.reconcile"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the compressed chaos soak (the ISSUE-8 acceptance run)
# ---------------------------------------------------------------------------


def _run_chaos_soak(seed=0):
    scenario = get_scenario("chaos")
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device",
                       resident=True, resident_audit_interval=0,
                       device_cycle_timeout_s=2.0,
                       device_recover_cycles=2)
    warm_device_path(plane)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=seed)
    return plane, driver, driver.run()


@pytest.mark.chaos
@pytest.mark.soak
def test_chaos_soak_zero_safety_violations():
    """Storm arrivals + estimator outage + device hang/raise + resident
    corruption, compressed: the circuit opens and half-open recovers,
    the backend degrades and re-arms, the audit-forced rebuild stays
    bit-exact, and the safety auditor reports ZERO conservation
    violations.  Runs with the runtime race detector ARMED (the ISSUE-19
    acceptance leg): a guarded-by mutation off-lock or an OwnerThread
    contract breach raises InvariantViolation mid-soak, and the
    order-inversion and deadlock-watchdog counters must not move."""
    from karmada_tpu.analysis import guards
    from karmada_tpu.utils import locks

    was = guards.armed()
    locks.reset_for_tests()  # clear order edges other tests recorded
    inv0 = locks._INVERSIONS.total()  # noqa: SLF001
    trips0 = locks._TRIPS.total()  # noqa: SLF001
    guards.arm()
    wd = locks.LockWatchdog(threshold_s=5.0, poll_s=0.2).start()
    try:
        plane, driver, p = _run_chaos_soak()
    finally:
        wd.stop()
        guards.arm(was)
    assert locks._INVERSIONS.total() - inv0 == 0, (  # noqa: SLF001
        locks.state_payload()["inversions"])
    assert locks._TRIPS.total() - trips0 == 0  # noqa: SLF001
    audit = p["safety_audit"]
    assert audit["violations"] == [], json.dumps(audit["violations"],
                                                 indent=2)
    fires = audit["fault_fires"]
    # every scheduled single-shot fault reached its seam
    assert fires.get("device.cycle") == 1
    assert fires.get("device.dispatch") == 1
    assert fires.get("resident.mirror") == 1
    assert fires.get("estimator.rpc", 0) > 0
    deltas = audit["metric_deltas"]
    # estimator outage: typed errors counted, circuit opened AND closed
    assert deltas["estimator_errors"] >= fires["estimator.rpc"]
    tos = [t["to"] for t in p["estimator_circuit"]["transitions"]]
    assert "open" in tos and "half-open" in tos and "closed" in tos
    assert all(s == "closed"
               for s in p["estimator_circuit"]["states"].values())
    # the hang degraded the backend; the cooldown re-armed it
    assert deltas["backend_degraded"] >= 1
    assert deltas["backend_rearmed"] >= 1
    assert plane.scheduler.backend == "device"
    # the dispatch raise was contained (bindings re-queued, not lost)
    assert deltas["cycle_faults"] >= 1
    # the corruption was caught by the forced audit and rebuilt
    assert deltas["resident_audits_mismatch"] == 1
    # conservation: nothing lost, nothing double-placed, queues drained
    cons = audit["conservation"]
    assert cons["double_placed"] == 0
    assert cons["unaccounted"] <= cons["shed_budget"]
    assert cons["scheduled"] + cons["queued_residual"] \
        + cons["unaccounted"] == cons["injected"]
    assert p["residual_queue"] == {"active": 0, "backoff": 0,
                                   "unschedulable": 0}
    # the soak really stressed the plane
    assert p["injected"] > 300 and p["scheduled"] > 300
    # chaos is disarmed after the run (no leakage into the next test)
    assert not chaos.armed()


@pytest.mark.chaos
@pytest.mark.soak
def test_chaos_soak_traffic_is_seed_deterministic():
    """Same seed -> identical arrival process and fault schedule (the
    virtual-clock event times are derived, not wall-dependent)."""
    s = get_scenario("chaos")
    model = ServiceModel()

    def arrivals(seed):
        clock = VirtualClock()
        plane = ServeSlice(s, clock, model)  # serial: arrivals only
        d = LoadDriver(plane, s, clock=clock, model=model, seed=seed)
        return list(d._arrivals), [t for t, _ in d._events]  # noqa: SLF001

    assert arrivals(11) == arrivals(11)
    assert arrivals(11)[0] != arrivals(12)[0]
