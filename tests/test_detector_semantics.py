"""Detector claim semantics: preemption Always/Never + Lazy activation.

Reference: pkg/detector/preemption.go:50-107 (preemptionEnabled + the
high-priority-PP > low-priority-PP > CPP rule) and detector.go:1485-1497
(Lazy ActivationPreference defers policy-driven changes until the resource
itself changes).
"""

from karmada_tpu.controllers.detector import (
    CLUSTER_POLICY_LABEL,
    POLICY_LABEL,
)
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.policy import (
    LAZY_ACTIVATION,
    ClusterPropagationPolicy,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.policy import REPLICA_SCHEDULING_DUPLICATED
from karmada_tpu.models.work import ResourceBinding


def nginx():
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "nginx", "namespace": "default"},
        "spec": {"replicas": 3},
    }


def pp(name, priority=0, preemption="Never", lazy=False, ns="default"):
    return PropagationPolicy(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED
                )
            ),
            priority=priority,
            preemption=preemption,
            activation_preference=LAZY_ACTIVATION if lazy else "",
        ),
    )


def cpp(name, priority=0, preemption="Never"):
    p = pp(name, priority, preemption, ns="")
    return ClusterPropagationPolicy(metadata=p.metadata, spec=p.spec)


def plane():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    return cp


def claimed_by(cp):
    obj = cp.store.get("Deployment", "default", "nginx")
    return (
        obj.metadata.labels.get(POLICY_LABEL),
        obj.metadata.labels.get(CLUSTER_POLICY_LABEL),
    )


def test_preemption_never_keeps_claim():
    cp = plane()
    cp.store.create(pp("low", priority=1))
    cp.apply(nginx())
    cp.tick()
    assert claimed_by(cp) == ("default/low", None)
    # higher priority but preemption Never: claim must NOT move
    cp.store.create(pp("high", priority=10, preemption="Never"))
    cp.tick()
    assert claimed_by(cp) == ("default/low", None)


def test_preemption_always_takes_claim():
    cp = plane()
    cp.store.create(pp("low", priority=1))
    cp.apply(nginx())
    cp.tick()
    cp.store.create(pp("high", priority=10, preemption="Always"))
    cp.tick()
    assert claimed_by(cp) == ("default/high", None)


def test_preemption_always_requires_higher_priority():
    cp = plane()
    cp.store.create(pp("first", priority=5))
    cp.apply(nginx())
    cp.tick()
    # same priority + Always: no preemption (strictly-higher rule)
    cp.store.create(pp("equal", priority=5, preemption="Always"))
    cp.tick()
    assert claimed_by(cp) == ("default/first", None)


def test_pp_preempts_cpp_with_always():
    cp = plane()
    cp.store.create(cpp("cluster-wide", priority=100))
    cp.apply(nginx())
    cp.tick()
    assert claimed_by(cp) == (None, "cluster-wide")
    # a PP with Always takes over regardless of priority (PP > CPP)
    cp.store.create(pp("local", priority=0, preemption="Always"))
    cp.tick()
    assert claimed_by(cp) == ("default/local", None)


def test_pp_does_not_preempt_cpp_with_never():
    cp = plane()
    cp.store.create(cpp("cluster-wide"))
    cp.apply(nginx())
    cp.tick()
    cp.store.create(pp("local", priority=50, preemption="Never"))
    cp.tick()
    assert claimed_by(cp) == (None, "cluster-wide")


def test_lazy_policy_update_deferred_until_resource_change():
    cp = plane()
    cp.store.create(pp("lazy", lazy=True))
    cp.apply(nginx())
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert rb.spec.conflict_resolution == "Abort"

    # change the policy: Lazy means existing claimed templates keep the OLD
    # binding content on a policy-driven reconcile
    def bump(p):
        p.spec.conflict_resolution = "Overwrite"

    cp.store.mutate(PropagationPolicy.KIND, "default", "lazy", bump)
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert rb.spec.conflict_resolution == "Abort", "lazy update applied too early"

    # the resource itself changing activates the new policy content
    manifest = nginx()
    manifest["spec"]["replicas"] = 4
    cp.apply(manifest)
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert rb.spec.conflict_resolution == "Overwrite"


def test_lazy_policy_does_not_claim_existing_until_resource_change():
    cp = plane()
    cp.apply(nginx())
    cp.tick()
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is None
    cp.store.create(pp("late-lazy", lazy=True))
    cp.tick()
    # policy-driven pass skips the lazy claim entirely
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is None
    # a template change picks it up
    manifest = nginx()
    manifest["spec"]["replicas"] = 5
    cp.apply(manifest)
    cp.tick()
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is not None
