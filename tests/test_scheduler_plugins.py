"""Out-of-tree scheduler plugin registry: an external Filter + Score plugin
must behave bit-identically on the serial, native (C++) and device (batched
solver) backends.

Reference: pkg/scheduler/framework/interface.go:45-66 (FilterPlugin /
ScorePlugin) + runtime/registry.go (named registry, `*,-Foo` enablement).
"""

from __future__ import annotations

import random

import pytest

from karmada_tpu import native
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.solver import solve
from karmada_tpu.scheduler.plugins import EXTRA_SCORE_CAP, PluginRegistry, REGISTRY
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    for name in ("NoSilver", "PreferEven", "Greedy"):
        REGISTRY.unregister(name)
    REGISTRY.set_enablement("*")


def mk_cluster(name, cpu=32000):
    return Cluster(
        metadata=ObjectMeta(name=name),
        spec=ClusterSpec(region="r1"),
        status=ClusterStatus(
            api_enablements=[APIEnablement(GVK[0], [GVK[1]])],
            resource_summary=ResourceSummary(
                allocatable={
                    "cpu": Quantity.from_milli(cpu),
                    "memory": Quantity.from_units(128),
                    "pods": Quantity.from_units(110),
                },
            ),
        ),
    )


def mk_items(names, n=10):
    rng = random.Random(4)
    placements = [
        Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))),
        # selection order is where scores bite: pick 3 of many
        Placement(
            spread_constraints=[SpreadConstraint(
                spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                min_groups=1, max_groups=3)],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))),
    ]
    items = []
    for b in range(n):
        spec = ResourceBindingSpec(
            resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                     namespace="ns", name=f"a{b}",
                                     uid=f"u{b}"),
            replicas=rng.choice([2, 4, 8]),
            replica_requirements=ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(rng.choice([100, 250]))}),
            placement=placements[b % len(placements)],
        )
        items.append((spec, ResourceBindingStatus()))
    return items


def filter_no_silver(placement, cluster):
    if cluster.name.endswith(("3", "7")):
        return "cluster(s) rejected by NoSilver plugin"
    return None


def score_prefer_even(placement, cluster):
    return 60 if int(cluster.name[-1]) % 2 == 0 else 0


def run_three_backends(items, clusters):
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    # serial
    serial_out = []
    for spec, st in items:
        try:
            serial_out.append(
                {tc.name: tc.replicas for tc in
                 serial.schedule(spec, st, clusters, cal)})
        except Exception as e:  # noqa: BLE001
            serial_out.append(type(e).__name__)
    # device
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    assert (batch.route == tensors.ROUTE_DEVICE).all()
    rep, sel, status = solve(batch)
    decoded = tensors.decode_result(batch, rep, sel, status, items=items)
    device_out = [
        (type(d).__name__ if isinstance(d, Exception)
         else {tc.name: tc.replicas for tc in d})
        for d in (decoded[b] for b in range(len(items)))
    ]
    # native
    native_out = None
    if native.available():
        snap = native.NativeSnapshot(clusters, native.collect_res_names(items))
        native_out = []
        for st_code, targets in native.schedule_batch_native(items, snap):
            if st_code == native.STATUS_OK:
                native_out.append({tc.name: tc.replicas for tc in targets})
            else:
                native_out.append({
                    native.STATUS_FIT_ERROR: "FitError",
                    native.STATUS_UNSCHEDULABLE: "UnschedulableError",
                    native.STATUS_NO_CLUSTER: "NoClusterAvailableError",
                }.get(st_code, f"status-{st_code}"))
    return serial_out, device_out, native_out


def test_plugin_filters_and_scores_agree_across_backends():
    clusters = [mk_cluster(f"m{i}", cpu=16000 + i * 4000) for i in range(10)]
    items = mk_items([c.name for c in clusters])

    REGISTRY.register_filter("NoSilver", filter_no_silver)
    REGISTRY.register_score("PreferEven", score_prefer_even)

    serial_out, device_out, native_out = run_three_backends(items, clusters)
    assert serial_out == device_out
    if native_out is not None:
        assert serial_out == native_out

    # the filter really fired: no schedule lands on m3/m7
    for out in serial_out:
        if isinstance(out, dict):
            assert "m3" not in out and "m7" not in out
    # the score really fired: selection-limited bindings (max_groups=3)
    # pick even-named clusters first
    sel_binding = serial_out[2]
    assert isinstance(sel_binding, dict)
    assert all(int(n[-1]) % 2 == 0 for n in sel_binding), sel_binding


def test_plugin_changes_results_vs_no_plugin():
    clusters = [mk_cluster(f"m{i}") for i in range(10)]
    items = mk_items([c.name for c in clusters])
    base_serial, base_device, _ = run_three_backends(items, clusters)

    REGISTRY.register_filter("NoSilver", filter_no_silver)
    REGISTRY.register_score("PreferEven", score_prefer_even)
    new_serial, new_device, _ = run_three_backends(items, clusters)
    assert new_serial != base_serial  # plugins actually changed outcomes
    assert new_serial == new_device

    # disable via the `*,-Name` flag syntax: back to baseline
    REGISTRY.set_enablement("*,-NoSilver,-PreferEven")
    off_serial, off_device, _ = run_three_backends(items, clusters)
    assert off_serial == base_serial
    assert off_device == base_device


def test_compact_path_parity_with_plugins():
    """C=600 > COMPACT_LANES: the score-aware top-K gather must keep the
    compact path bit-identical to serial when plugin scores reorder the
    selection."""
    clusters = [mk_cluster(f"m{i:03d}", cpu=8000 + (i % 13) * 1000)
                for i in range(600)]
    items = mk_items([c.name for c in clusters], n=8)

    REGISTRY.register_score("PreferEven", score_prefer_even)
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    assert batch.C > tensors.COMPACT_LANES
    rep, sel, status = solve(batch)
    decoded = tensors.decode_result(batch, rep, sel, status, items=items)
    for b, (spec, st) in enumerate(items):
        want = {tc.name: tc.replicas
                for tc in serial.schedule(spec, st, clusters, cal)}
        got = {tc.name: tc.replicas for tc in decoded[b]}
        assert got == want, (b, got, want)


def test_score_clamp_and_registry_semantics():
    r = PluginRegistry()
    r.register_score("Greedy", lambda p, c: 10_000)
    assert r.extra_score(Placement(), mk_cluster("m0")) == EXTRA_SCORE_CAP
    r.register_score("Negative", lambda p, c: -50)
    # sum then clamp: 10_000 - 50 still clamps to cap
    assert r.extra_score(Placement(), mk_cluster("m0")) == EXTRA_SCORE_CAP
    r.set_enablement("-Greedy")  # no star: everything else off too
    assert r.extra_score(Placement(), mk_cluster("m0")) == 0
    r.set_enablement("Negative")
    assert r.extra_score(Placement(), mk_cluster("m0")) == 0  # clamp floor
    gen0 = r.generation
    r.unregister("Greedy")
    assert r.generation > gen0


def test_encoder_cache_invalidated_on_plugin_change():
    clusters = [mk_cluster(f"m{i}") for i in range(6)]
    items = mk_items([c.name for c in clusters], n=4)
    cache = tensors.EncoderCache()
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()
    b0 = tensors.encode_batch(items, cindex, est, cache=cache)
    # real placement rows only (the P axis is pow2-padded with False rows)
    assert b0.pl_mask[b0.placement_id[:4], :6].all()
    REGISTRY.register_filter("NoSilver", filter_no_silver)
    b1 = tensors.encode_batch(items, cindex, est, cache=cache)
    assert not b1.pl_mask[b1.placement_id[0], 3]  # m3 masked out now
