"""Declarative interpreter tier: sandboxed data-driven customizations.

Reference: pkg/resourceinterpreter/customized/declarative/luavm/lua.go
(user scripts from ResourceInterpreterCustomization objects, sandboxed,
ranked above the third-party bundle and native defaults) and
default/thirdparty/resourcecustomizations/ (the data-only bundle).
"""

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.interpreter.declarative import ScriptError, compile_script
from karmada_tpu.interpreter.interpreter import ResourceInterpreter
from karmada_tpu.models.config import (
    CustomizationTarget,
    ResourceInterpreterCustomization,
    ResourceInterpreterCustomizationSpec,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DUPLICATED,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding


# -- sandbox ---------------------------------------------------------------


def test_sandbox_rejects_imports_and_attributes():
    with pytest.raises(ScriptError):
        compile_script("__import__('os')")
    with pytest.raises(ScriptError):
        compile_script("obj.__class__")
    with pytest.raises(ScriptError):
        compile_script("(lambda: 1)()")
    with pytest.raises(ScriptError):
        compile_script("x := 5")


def test_sandbox_evaluates_expressions():
    fn = compile_script("get(obj, 'spec.replicas', 0) * 2")
    assert fn({"obj": {"spec": {"replicas": 3}}}) == 6
    fn = compile_script("{'n': max([i for i in [1, 5, 3]])}")
    assert fn({}) == {"n": 5}
    fn = compile_script("quantity('500m') + quantity('1')")
    assert fn({}) == 1500


def test_sandbox_set_is_copy_on_write():
    fn = compile_script("set(obj, 'spec.replicas', replicas)")
    src = {"spec": {"replicas": 1}}
    out = fn({"obj": src, "replicas": 9})
    assert out["spec"]["replicas"] == 9
    assert src["spec"]["replicas"] == 1


# -- third-party bundle ----------------------------------------------------


def rollout(replicas=5):
    return {
        "apiVersion": "argoproj.io/v1alpha1", "kind": "Rollout",
        "metadata": {"name": "r", "namespace": "default", "generation": 2},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "250m"}}}]}}},
        "status": {"observedGeneration": 2, "availableReplicas": replicas,
                   "replicas": replicas, "readyReplicas": replicas,
                   "updatedReplicas": replicas, "phase": "Healthy"},
    }


def test_thirdparty_rollout_replicas_and_health():
    interp = ResourceInterpreter()
    replicas, req = interp.get_replicas(rollout())
    assert replicas == 5
    assert req.resource_request["cpu"].milli == 250
    assert interp.interpret_health(rollout()) == "Healthy"
    revised = interp.revise_replica(rollout(), 2)
    assert revised["spec"]["replicas"] == 2


def test_thirdparty_cloneset_replicas():
    interp = ResourceInterpreter()
    manifest = {
        "apiVersion": "apps.kruise.io/v1alpha1", "kind": "CloneSet",
        "metadata": {"name": "cs", "namespace": "default"},
        "spec": {"replicas": 7, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"memory": "2Gi"}}}]}}},
    }
    replicas, req = interp.get_replicas(manifest)
    assert replicas == 7
    assert req.resource_request["memory"].value() == 2 * 1024**3


# -- declarative tier end to end -------------------------------------------


def crd_workload(replicas=4):
    return {
        "apiVersion": "example.io/v1", "kind": "Widget",
        "metadata": {"name": "w", "namespace": "default"},
        "spec": {"size": replicas},  # replicas live in a custom field
    }


def customization(name="widget-cust"):
    return ResourceInterpreterCustomization(
        metadata=ObjectMeta(name=name),
        spec=ResourceInterpreterCustomizationSpec(
            target=CustomizationTarget(api_version="example.io/v1", kind="Widget"),
            customizations={
                "InterpretReplica": "get(obj, 'spec.size', 0)",
                "ReviseReplica": "set(obj, 'spec.size', replicas)",
                "InterpretStatus": "{'size': get(obj, 'status.size', 0)}",
                "InterpretHealth": "get(obj, 'status.size', 0) >= get(obj, 'spec.size', 0)",
            },
        ),
    )


def test_customization_changes_get_replicas_without_framework_code():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1")
    cp.tick()
    # before the customization: unknown kind -> 0 replicas
    assert cp.interpreter.get_replicas(crd_workload())[0] == 0
    cp.store.create(customization())
    assert cp.interpreter.get_replicas(crd_workload())[0] == 4
    # live update through the store changes behavior again
    def double(c):
        c.spec.customizations["InterpretReplica"] = "get(obj, 'spec.size', 0) * 2"
    cp.store.mutate(
        ResourceInterpreterCustomization.KIND, "", "widget-cust", double
    )
    assert cp.interpreter.get_replicas(crd_workload())[0] == 8
    # delete: back to native (which declines the unknown kind)
    cp.store.delete(ResourceInterpreterCustomization.KIND, "", "widget-cust")
    assert cp.interpreter.get_replicas(crd_workload())[0] == 0


def test_customization_drives_propagation_pipeline():
    """A CRD the framework has never seen schedules via its customization:
    detector reads replicas from spec.size, binding revises the same field."""
    cp = ControlPlane(backend="serial")
    m = cp.add_member("m1", cpu_milli=64_000)
    from karmada_tpu.models.cluster import APIEnablement

    m.api_enablements.append(APIEnablement("example.io/v1", ["Widget"]))
    cp.tick()
    cp.store.create(customization())
    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="example.io/v1", kind="Widget")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        ),
    ))
    cp.apply(crd_workload(replicas=4))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "w-widget")
    assert rb.spec.replicas == 4
    applied = cp.members["m1"].get("Widget", "default", "w")
    assert applied is not None
    assert applied.manifest["spec"]["size"] == 4


def test_invalid_script_never_shadows_native():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1")
    bad = ResourceInterpreterCustomization(
        metadata=ObjectMeta(name="bad"),
        spec=ResourceInterpreterCustomizationSpec(
            target=CustomizationTarget(api_version="apps/v1", kind="Deployment"),
            customizations={"InterpretReplica": "import os"},
        ),
    )
    cp.store.create(bad)
    manifest = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "d", "namespace": "default"},
        "spec": {"replicas": 3},
    }
    assert cp.interpreter.get_replicas(manifest)[0] == 3  # native default


def test_alphabetical_priority_between_customizations():
    cp = ControlPlane(backend="serial")
    cp.add_member("m1")
    a = customization("a-first")
    a.spec.customizations = {"InterpretReplica": "111"}
    z = customization("z-last")
    z.spec.customizations = {"InterpretReplica": "999"}
    cp.store.create(z)
    cp.store.create(a)
    assert cp.interpreter.get_replicas(crd_workload())[0] == 111


def test_thirdparty_flink_volcano_kubeflow_flux_spark():
    """Round-3 bundle additions (default/thirdparty/resourcecustomizations/
    flink.apache.org, batch.volcano.sh, kubeflow.org, helm.toolkit.fluxcd.io,
    sparkoperator.k8s.io)."""
    interp = ResourceInterpreter()

    flink = {"apiVersion": "flink.apache.org/v1beta1", "kind": "FlinkDeployment",
             "metadata": {"namespace": "d", "name": "f"},
             "spec": {"taskManager": {"replicas": 4,
                                      "resource": {"cpu": 2, "memory": "2Gi"}}},
             "status": {"lifecycleState": "STABLE",
                        "jobStatus": {"state": "RUNNING"}}}
    replicas, req = interp.get_replicas(flink)
    assert replicas == 4 and req.resource_request["cpu"].milli == 2000
    assert interp.interpret_health(flink) == "Healthy"
    assert interp.revise_replica(flink, 6)["spec"]["taskManager"]["replicas"] == 6

    volcano = {"apiVersion": "batch.volcano.sh/v1alpha1", "kind": "Job",
               "metadata": {"namespace": "d", "name": "v"},
               "spec": {"tasks": [{"replicas": 2}, {"replicas": 3}]},
               "status": {"state": {"phase": "Running"}, "running": 5}}
    assert interp.get_replicas(volcano)[0] == 5
    assert interp.interpret_health(volcano) == "Healthy"
    assert interp.reflect_status(volcano)["running"] == 5

    tfjob = {"apiVersion": "kubeflow.org/v1", "kind": "TFJob",
             "metadata": {"namespace": "d", "name": "t"},
             "spec": {"tfReplicaSpecs": {"PS": {"replicas": 1},
                                         "Worker": {"replicas": 3}}},
             "status": {"conditions": [
                 {"type": "Running", "status": "True"}]}}
    assert interp.get_replicas(tfjob)[0] == 4
    assert interp.interpret_health(tfjob) == "Healthy"

    helm = {"apiVersion": "helm.toolkit.fluxcd.io/v2beta1", "kind": "HelmRelease",
            "metadata": {"namespace": "d", "name": "h"},
            "status": {"conditions": [{"type": "Ready", "status": "False"}]}}
    assert interp.get_replicas(helm)[0] == 0
    assert interp.interpret_health(helm) == "Unhealthy"

    spark = {"apiVersion": "sparkoperator.k8s.io/v1beta2",
             "kind": "SparkApplication",
             "metadata": {"namespace": "d", "name": "s"},
             "spec": {"executor": {"instances": 3}},
             "status": {"applicationState": {"state": "RUNNING"}}}
    assert interp.get_replicas(spark)[0] == 4  # driver + executors
    assert interp.interpret_health(spark) == "Healthy"
    revised = interp.revise_replica(spark, 6)
    assert revised["spec"]["executor"]["instances"] == 5


def test_thirdparty_divisibility_roundtrips():
    """ReviseReplica must round-trip with InterpretReplica for every
    divisible bundle kind (review finding: otherwise a Divided placement
    over-deploys on every member)."""
    interp = ResourceInterpreter()

    # Volcano: sequential fill across tasks + minAvailable clamp
    volcano = {"apiVersion": "batch.volcano.sh/v1alpha1", "kind": "Job",
               "metadata": {"namespace": "d", "name": "v"},
               "spec": {"minAvailable": 5,
                        "tasks": [{"name": "master", "replicas": 1},
                                  {"name": "worker", "replicas": 4}]}}
    revised = interp.revise_replica(volcano, 3)
    assert [t["replicas"] for t in revised["spec"]["tasks"]] == [1, 2]
    assert revised["spec"]["minAvailable"] == 3
    assert interp.get_replicas(revised)[0] == 3
    # original untouched (copy-on-write set())
    assert [t["replicas"] for t in volcano["spec"]["tasks"]] == [1, 4]

    # TFJob: Worker absorbs the division, fixed roles keep their counts
    tfjob = {"apiVersion": "kubeflow.org/v1", "kind": "TFJob",
             "metadata": {"namespace": "d", "name": "t"},
             "spec": {"tfReplicaSpecs": {"PS": {"replicas": 1},
                                         "Worker": {"replicas": 3}}}}
    revised = interp.revise_replica(tfjob, 2)
    assert revised["spec"]["tfReplicaSpecs"]["Worker"]["replicas"] == 1
    assert interp.get_replicas(revised)[0] == 2

    # Spark: explicit instances: 0 round-trips (driver-only == 1 replica)
    spark = {"apiVersion": "sparkoperator.k8s.io/v1beta2",
             "kind": "SparkApplication",
             "metadata": {"namespace": "d", "name": "s"},
             "spec": {"executor": {"instances": 3}}}
    revised = interp.revise_replica(spark, 1)
    assert revised["spec"]["executor"]["instances"] == 0
    assert interp.get_replicas(revised)[0] == 1

    # Flink: scale-to-zero round-trips
    flink = {"apiVersion": "flink.apache.org/v1beta1", "kind": "FlinkDeployment",
             "metadata": {"namespace": "d", "name": "f"},
             "spec": {"taskManager": {"replicas": 4}}}
    revised = interp.revise_replica(flink, 0)
    assert interp.get_replicas(revised)[0] == 0


def test_thirdparty_volcano_aggregate_status():
    interp = ResourceInterpreter()
    from karmada_tpu.models.work import AggregatedStatusItem

    volcano = {"apiVersion": "batch.volcano.sh/v1alpha1", "kind": "Job",
               "metadata": {"namespace": "d", "name": "v"},
               "spec": {"tasks": [{"replicas": 4}]}}
    items = [AggregatedStatusItem(cluster_name="m1",
                                  status={"running": 2, "succeeded": 0, "failed": 0}),
             AggregatedStatusItem(cluster_name="m2",
                                  status={"running": 3, "succeeded": 1, "failed": 0})]
    merged = interp.aggregate_status(volcano, items)
    assert merged["status"]["running"] == 5
    assert merged["status"]["state"]["phase"] == "Running"


def test_thirdparty_kruise_family():
    """Round-3 bundle completion: the remaining Kruise kinds
    (default/thirdparty/resourcecustomizations/apps.kruise.io/)."""
    interp = ResourceInterpreter()

    ds = {"apiVersion": "apps.kruise.io/v1alpha1", "kind": "DaemonSet",
          "metadata": {"namespace": "d", "name": "ds", "generation": 2},
          "status": {"observedGeneration": 2, "desiredNumberScheduled": 3,
                     "updatedNumberScheduled": 3, "numberAvailable": 3,
                     "numberReady": 3, "currentNumberScheduled": 3}}
    assert interp.get_replicas(ds)[0] == 0  # not divisible
    assert interp.interpret_health(ds) == "Healthy"
    ds["status"]["numberAvailable"] = 1  # rollout not available yet
    assert interp.interpret_health(ds) == "Unhealthy"

    ss = {"apiVersion": "apps.kruise.io/v1alpha1", "kind": "SidecarSet",
          "metadata": {"namespace": "d", "name": "ss"},
          "status": {"matchedPods": 0}}
    assert interp.interpret_health(ss) == "Healthy"  # nothing to update
    ss["status"] = {"matchedPods": 4, "updatedPods": 2}
    assert interp.interpret_health(ss) == "Unhealthy"

    ud = {"apiVersion": "apps.kruise.io/v1alpha1", "kind": "UnitedDeployment",
          "metadata": {"namespace": "d", "name": "ud", "generation": 1},
          "spec": {"replicas": 6, "template": {"statefulSetTemplate": {
              "spec": {"template": {"spec": {"containers": [
                  {"name": "c",
                   "resources": {"requests": {"cpu": "500m"}}}]}}}}}},
          "status": {"observedGeneration": 1, "updatedReplicas": 6}}
    replicas, req = interp.get_replicas(ud)
    assert replicas == 6 and req.resource_request["cpu"].milli == 500
    revised = interp.revise_replica(ud, 2)
    assert revised["spec"]["replicas"] == 2
    assert interp.interpret_health(ud) == "Healthy"

    bj = {"apiVersion": "apps.kruise.io/v1alpha1", "kind": "BroadcastJob",
          "metadata": {"namespace": "d", "name": "bj"},
          "spec": {"parallelism": 5},
          "status": {"desired": 5, "active": 5, "failed": 0, "succeeded": 0}}
    assert interp.get_replicas(bj)[0] == 5
    assert interp.revise_replica(bj, 2)["spec"]["parallelism"] == 2
    assert interp.interpret_health(bj) == "Healthy"
    bj["status"]["failed"] = 1
    assert interp.interpret_health(bj) == "Unhealthy"

    from karmada_tpu.models.work import AggregatedStatusItem

    acj = {"apiVersion": "apps.kruise.io/v1alpha1", "kind": "AdvancedCronJob",
           "metadata": {"namespace": "d", "name": "acj"}}
    merged = interp.aggregate_status(acj, [
        AggregatedStatusItem(cluster_name="m1", status={
            "active": [{"name": "j1"}], "lastScheduleTime": "t1"}),
        AggregatedStatusItem(cluster_name="m2", status={
            "active": [{"name": "j2"}], "lastScheduleTime": "t2"}),
    ])
    assert len(merged["status"]["active"]) == 2
    assert merged["status"]["lastScheduleTime"] == "t2"


def test_thirdparty_workflow_and_notebook():
    interp = ResourceInterpreter()

    wf = {"apiVersion": "argoproj.io/v1alpha1", "kind": "Workflow",
          "metadata": {"namespace": "d", "name": "wf"},
          "spec": {"parallelism": 4}, "status": {"phase": "Running"}}
    assert interp.get_replicas(wf)[0] == 4
    assert interp.revise_replica(wf, 2)["spec"]["parallelism"] == 2
    assert interp.interpret_health(wf) == "Healthy"
    wf["status"]["phase"] = "Failed"
    assert interp.interpret_health(wf) == "Unhealthy"

    nb = {"apiVersion": "kubeflow.org/v1", "kind": "Notebook",
          "metadata": {"namespace": "d", "name": "nb"},
          "spec": {"template": {"spec": {"containers": [
              {"name": "c", "resources": {"requests": {"memory": "1Gi"}}}]}}},
          "status": {"containerState": {
              "waiting": {"reason": "ContainerCreating"}}}}
    assert interp.get_replicas(nb)[0] == 1
    assert interp.interpret_health(nb) == "Healthy"  # still creating
    nb["status"]["containerState"] = {"waiting": {"reason": "CrashLoopBackOff"}}
    assert interp.interpret_health(nb) == "Unhealthy"
    nb["status"]["containerState"] = {"running": {"startedAt": "t"}}
    assert interp.interpret_health(nb) == "Healthy"


def test_thirdparty_mpijob_components_and_revise():
    interp = ResourceInterpreter()
    mpi = {"apiVersion": "kubeflow.org/v2beta1", "kind": "MPIJob",
           "metadata": {"namespace": "d", "name": "mpi"},
           "spec": {"mpiReplicaSpecs": {"Launcher": {"replicas": 1},
                                        "Worker": {"replicas": 4}}},
           "status": {"conditions": [{"type": "Running", "status": "True"}]}}
    assert interp.get_replicas(mpi)[0] == 5
    comps = {c.name: c.replicas for c in interp.get_components(mpi)}
    assert comps == {"Launcher": 1, "Worker": 4}
    revised = interp.revise_replica(mpi, 3)
    assert revised["spec"]["mpiReplicaSpecs"]["Worker"]["replicas"] == 2
    assert interp.get_replicas(revised)[0] == 3
    assert interp.interpret_health(mpi) == "Healthy"
    mpi["status"]["conditions"].append({"type": "Failed", "status": "True"})
    assert interp.interpret_health(mpi) == "Unhealthy"


def test_thirdparty_gitops_and_policy_kinds():
    """Flux kustomize/source kinds gate health on the Ready condition's
    REASON, not just its status; Kyverno prefers the status.ready bool."""
    interp = ResourceInterpreter()

    def ready(reason):
        return [{"type": "Ready", "status": "True", "reason": reason}]

    km = {"apiVersion": "kustomize.toolkit.fluxcd.io/v1", "kind": "Kustomization",
          "metadata": {"namespace": "d", "name": "k"},
          "status": {"conditions": ready("ReconciliationSucceeded")}}
    assert interp.interpret_health(km) == "Healthy"
    km["status"]["conditions"] = ready("Progressing")
    assert interp.interpret_health(km) == "Unhealthy"

    for api, kind, reason in (
        ("source.toolkit.fluxcd.io/v1", "GitRepository", "Succeeded"),
        ("source.toolkit.fluxcd.io/v1beta2", "Bucket", "Succeeded"),
        ("source.toolkit.fluxcd.io/v1beta2", "HelmChart", "ChartPullSucceeded"),
        ("source.toolkit.fluxcd.io/v1beta2", "HelmRepository",
         "IndexationSucceeded"),
        ("source.toolkit.fluxcd.io/v1beta2", "OCIRepository", "Succeeded"),
    ):
        obj = {"apiVersion": api, "kind": kind,
               "metadata": {"namespace": "d", "name": "x"},
               "status": {"conditions": ready(reason)}}
        assert interp.interpret_health(obj) == "Healthy", kind
        assert interp.get_replicas(obj)[0] == 0
        obj["status"]["conditions"] = ready("FetchFailed")
        assert interp.interpret_health(obj) == "Unhealthy", kind

    for kind in ("ClusterPolicy", "Policy"):
        pol = {"apiVersion": "kyverno.io/v1", "kind": kind,
               "metadata": {"namespace": "d", "name": "p"},
               "status": {"ready": True}}
        assert interp.interpret_health(pol) == "Healthy", kind
        pol["status"] = {"ready": False,
                         "conditions": ready("Succeeded")}
        # explicit ready: false wins over a stale Ready condition
        assert interp.interpret_health(pol) == "Unhealthy", kind
        pol["status"] = {"conditions": ready("Succeeded")}
        assert interp.interpret_health(pol) == "Healthy", kind
