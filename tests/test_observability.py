"""Metrics registry + event recorder, and their wiring through the control
plane (reference pkg/scheduler/metrics/metrics.go, pkg/metrics/cluster.go,
pkg/events/events.go)."""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    ClusterPreferences,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.utils import events as ev
from karmada_tpu.utils.metrics import Counter, Gauge, Histogram, Registry


def test_counter_gauge_histogram_basics():
    r = Registry()
    c = r.counter("c_total", "a counter", ("k",))
    c.inc(k="x")
    c.inc(2, k="x")
    c.inc(k="y")
    assert c.value(k="x") == 3 and c.value(k="y") == 1

    g = r.gauge("g", "a gauge", ("k",))
    g.set(5, k="x")
    g.add(-2, k="x")
    assert g.value(k="x") == 3

    h = r.histogram("h_seconds", "a histogram", ("k",), buckets=[0.1, 1, 10])
    for v in (0.05, 0.5, 5, 50):
        h.observe(v, k="x")
    assert h.count(k="x") == 4
    assert h.sum(k="x") == 55.55

    dump = r.dump()
    assert '# TYPE c_total counter' in dump
    assert 'c_total{k="x"} 3.0' in dump
    assert 'h_seconds_bucket{k="x",le="+Inf"} 4' in dump
    assert 'h_seconds_count{k="x"} 4' in dump


def test_registry_register_is_idempotent():
    r = Registry()
    a = r.counter("same", "one")
    b = r.counter("same", "two")
    assert a is b


def test_event_recorder_coalesces_and_bounds():
    clock = {"t": 0.0}
    rec = ev.EventRecorder(capacity=3, now=lambda: clock["t"])
    ref = ev.ObjectRef(kind="ResourceBinding", namespace="ns", name="a")
    rec.event(ref, ev.TYPE_WARNING, "R", "same message")
    clock["t"] = 5.0
    rec.event(ref, ev.TYPE_WARNING, "R", "same message")
    got = rec.list(kind="ResourceBinding")
    assert len(got) == 1 and got[0].count == 2
    assert got[0].first_timestamp == 0.0 and got[0].last_timestamp == 5.0
    # capacity bound evicts oldest
    for i in range(4):
        rec.event(ev.ObjectRef(kind="K", name=f"n{i}"), ev.TYPE_NORMAL, "R", "m")
    assert len(rec.list()) == 3


def test_control_plane_emits_metrics_and_events():
    cp = ControlPlane()
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))),
        ),
    ))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 2, "template": {"spec": {"containers": [
                  {"name": "a", "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()

    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert rb.spec.clusters

    # events: schedule success + work sync success + cluster ready
    reasons = {e.reason for e in cp.events()}
    assert ev.REASON_SCHEDULE_BINDING_SUCCEED in reasons
    assert ev.REASON_SYNC_WORKLOAD_SUCCEED in reasons
    assert ev.REASON_CLUSTER_READY in reasons
    per_binding = cp.events(kind="ResourceBinding", name="app-deployment")
    assert any(e.reason == ev.REASON_SCHEDULE_BINDING_SUCCEED for e in per_binding)

    # metrics: attempts counted, per-step latency observed, gauges exported
    dump = cp.metrics_dump()
    assert 'karmada_scheduler_schedule_attempts_total{result="scheduled"' in dump
    assert "karmada_scheduler_scheduling_algorithm_duration_seconds" in dump
    assert 'karmada_cluster_ready_state{cluster_name="m1"} 1.0' in dump
    assert "karmada_work_sync_workload_duration_seconds" in dump
    assert 'karmada_scheduler_queue_depth{queue="active"} 0' in dump


def test_failure_schedules_record_error_metrics_and_events():
    from karmada_tpu.scheduler.metrics import SCHEDULE_ATTEMPTS

    before = SCHEDULE_ATTEMPTS.value(result="error", schedule_type="reconcile")
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(),
        ),
    ))
    # no member enables batch/v1 CronJob-like kind: force FitError via affinity
    from karmada_tpu.models.policy import ClusterAffinity

    cp.store.mutate("PropagationPolicy", "default", "pp", lambda p: setattr(
        p.spec.placement, "cluster_affinity",
        ClusterAffinity(cluster_names=["absent"])))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 1, "template": {"spec": {"containers": [
                  {"name": "a"}]}}}})
    cp.tick()
    after = SCHEDULE_ATTEMPTS.value(result="error", schedule_type="reconcile")
    assert after > before
    warn = [e for e in cp.events(kind="ResourceBinding")
            if e.reason == ev.REASON_SCHEDULE_BINDING_FAILED]
    assert warn and warn[0].type == ev.TYPE_WARNING
