"""Multi-version API serving + conversion (CRD conversion-webhook parity).

The reference serves v1alpha1/v1alpha2 pairs in the work group and
converts through the webhook's /convert endpoint
(cmd/webhook/app/webhook.go:186-232, pkg/apis/work).  Here the storage
version is the typed dataclass; `Work` is additionally served at
work.karmada.io/v1alpha2 where spec.suspendDispatching is renamed to
spec.suspend.  The store round-trips ONE schema; reads, watches, applies
and /convert speak any served version (models/conversion.py).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.codec import from_manifest_typed, to_manifest_typed
from karmada_tpu.models.conversion import REGISTRY, WORK_V1ALPHA2
from karmada_tpu.models.work import Work
from karmada_tpu.search.httpapi import QueryPlaneServer

V1 = Work.API_VERSION  # the storage version
WORK_V2_MANIFEST = {
    "apiVersion": WORK_V1ALPHA2, "kind": "Work",
    "metadata": {"name": "w1", "namespace": "karmada-es-m1"},
    "spec": {
        "suspend": True,
        "workload": [{"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "cm"}}],
    },
}


def test_served_versions_and_storage_version():
    assert REGISTRY.storage_version("Work") == V1
    assert set(REGISTRY.served_versions("Work")) == {V1, WORK_V1ALPHA2}
    assert REGISTRY.served("Work", V1)
    assert REGISTRY.served("Work", WORK_V1ALPHA2)
    assert not REGISTRY.served("Work", "work.karmada.io/v9")


def test_convert_routes_through_the_storage_hub():
    v1 = REGISTRY.convert(WORK_V2_MANIFEST, V1)
    assert v1["apiVersion"] == V1
    assert v1["spec"]["suspendDispatching"] is True
    assert "suspend" not in v1["spec"]
    assert v1["spec"]["workload"], "untouched fields must survive"

    back = REGISTRY.convert(v1, WORK_V1ALPHA2)
    assert back["apiVersion"] == WORK_V1ALPHA2
    assert back["spec"]["suspend"] is True
    assert "suspendDispatching" not in back["spec"]
    # converting to the version it already has is the identity
    assert REGISTRY.convert(v1, V1) is v1


def test_convert_rejects_unserved_versions():
    with pytest.raises(KeyError):
        REGISTRY.convert(WORK_V2_MANIFEST, "work.karmada.io/v9")
    with pytest.raises(KeyError):
        REGISTRY.convert(
            {"apiVersion": "work.karmada.io/v9", "kind": "Work"}, V1)


def test_decode_served_version_into_storage_model():
    w = from_manifest_typed(WORK_V2_MANIFEST)
    assert isinstance(w, Work)
    assert w.spec.suspend_dispatching is True
    assert w.spec.workload and w.spec.workload[0]["kind"] == "ConfigMap"


def test_encode_round_trips_both_versions():
    w = from_manifest_typed(WORK_V2_MANIFEST)
    v1 = to_manifest_typed(w)
    assert v1["apiVersion"] == V1 and v1["spec"]["suspendDispatching"] is True
    v2 = to_manifest_typed(w, version=WORK_V1ALPHA2)
    assert v2["apiVersion"] == WORK_V1ALPHA2
    assert v2["spec"]["suspend"] is True
    assert "suspendDispatching" not in v2["spec"]
    # full loop: decode what we encoded, nothing drifts
    again = from_manifest_typed(v2)
    assert again == w


def test_binding_v1alpha1_structural_moves():
    """The reference's REAL legacy pair (work/v1alpha1 bindings,
    binding_types_conversion.go:77-128): replicas + per-replica demand
    live INSIDE spec.resource at v1alpha1 and are hoisted to spec-level
    fields in the hub — a structural MOVE, not a rename."""
    from karmada_tpu.models.conversion import BINDING_V1ALPHA1
    from karmada_tpu.models.work import ResourceBinding

    legacy = {
        "apiVersion": BINDING_V1ALPHA1, "kind": "ResourceBinding",
        "metadata": {"name": "rb", "namespace": "default"},
        "spec": {
            "resource": {"apiVersion": "apps/v1", "kind": "Deployment",
                         "name": "app", "replicas": 4,
                         "replicaResourceRequirements": {"cpu": "500m"}},
            "clusters": [{"name": "m1", "replicas": 4}],
        },
    }
    rb = from_manifest_typed(legacy)
    assert isinstance(rb, ResourceBinding)
    assert rb.spec.replicas == 4
    assert str(rb.spec.replica_requirements.resource_request["cpu"]) == "500m"
    assert rb.spec.resource.kind == "Deployment"
    assert rb.spec.clusters[0].name == "m1"

    # down-convert: the moves reverse, and hub-only machinery is dropped
    # exactly like ConvertBindingSpecFromHub (placement has no v1alpha1 home)
    import dataclasses

    from karmada_tpu.models.policy import Placement

    rb2 = dataclasses.replace(
        rb, spec=dataclasses.replace(rb.spec, placement=Placement()))
    down = to_manifest_typed(rb2, version=BINDING_V1ALPHA1)
    assert down["apiVersion"] == BINDING_V1ALPHA1
    assert down["spec"]["resource"]["replicas"] == 4
    assert down["spec"]["resource"]["replicaResourceRequirements"] == {
        "cpu": "500m"}
    assert "replicas" not in down["spec"]
    assert "replicaRequirements" not in down["spec"]
    assert "placement" not in down["spec"]

    # and the legacy form is a fixed point through the hub
    assert from_manifest_typed(down).spec.replicas == 4


def test_cluster_resource_binding_served_at_v1alpha1():
    from karmada_tpu.models.conversion import BINDING_V1ALPHA1

    assert REGISTRY.served("ClusterResourceBinding", BINDING_V1ALPHA1)
    out = REGISTRY.convert(
        {"apiVersion": "work.karmada.io/v1alpha2",
         "kind": "ClusterResourceBinding",
         "metadata": {"name": "crb"},
         "spec": {"replicas": 2,
                  "resource": {"kind": "ClusterRole", "name": "r"}}},
        BINDING_V1ALPHA1)
    assert out["spec"]["resource"]["replicas"] == 2


def test_randomized_work_manifests_round_trip_both_versions():
    """Property: decode -> encode at either served version -> decode is the
    identity for arbitrary Work content (hypothesis-driven; the converter
    must never eat fields it does not know about)."""
    pytest.importorskip("hypothesis")
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    json_scalars = st.one_of(st.booleans(), st.integers(-2**31, 2**31),
                             st.text(max_size=12))
    manifests = st.lists(
        st.fixed_dictionaries({
            "apiVersion": st.sampled_from(["v1", "apps/v1"]),
            "kind": st.sampled_from(["ConfigMap", "Deployment"]),
            "metadata": st.fixed_dictionaries(
                {"name": st.text(min_size=1, max_size=8)}),
        }, optional={"data": st.dictionaries(
            st.text(min_size=1, max_size=6), json_scalars, max_size=3)}),
        max_size=3)

    @settings(max_examples=30, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(suspend=st.booleans(), workload=manifests,
           version=st.sampled_from([V1, WORK_V1ALPHA2]),
           name=st.text(min_size=1, max_size=10))
    def prop(suspend, workload, version, name):
        src = {"apiVersion": WORK_V1ALPHA2, "kind": "Work",
               "metadata": {"name": name, "namespace": "ns"},
               "spec": {"suspend": suspend, "workload": workload}}
        w = from_manifest_typed(src)
        assert w.spec.suspend_dispatching is suspend
        assert w.spec.workload == workload
        encoded = to_manifest_typed(w, version=version)
        assert encoded["apiVersion"] == version
        again = from_manifest_typed(encoded)
        assert again == w

    prop()


@pytest.fixture
def served_plane():
    cp = ControlPlane()
    srv = QueryPlaneServer(cp.store, cp.members, cp.cluster_proxy,
                           apply_fn=cp.apply)
    url = srv.start()
    yield cp, url
    srv.stop()


def get_json(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read())


def post_json(url, path, body):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_store_read_in_either_version_over_http(served_plane):
    cp, url = served_plane
    cp.apply(WORK_V2_MANIFEST)  # apply at v2; the store holds storage schema
    stored = cp.store.get("Work", "karmada-es-m1", "w1")
    assert stored.spec.suspend_dispatching is True

    v1 = get_json(url, "/api/Work/karmada-es-m1/w1")
    assert v1["apiVersion"] == V1
    assert v1["spec"]["suspendDispatching"] is True

    v2 = get_json(url, "/api/Work/karmada-es-m1/w1"
                       f"?version={WORK_V1ALPHA2}")
    assert v2["apiVersion"] == WORK_V1ALPHA2
    assert v2["spec"]["suspend"] is True
    assert "suspendDispatching" not in v2["spec"]

    listed = get_json(url, f"/api/Work?version={WORK_V1ALPHA2}")
    assert listed and listed[0]["spec"]["suspend"] is True

    with pytest.raises(urllib.error.HTTPError) as ei:
        get_json(url, "/api/Work?version=work.karmada.io/v9")
    assert ei.value.code == 400


def test_store_watch_in_either_version_over_http(served_plane):
    cp, url = served_plane
    got = {}

    def consume(version, key):
        path = f"/api-watch/Work?timeout=3&version={version}"
        events = []
        with urllib.request.urlopen(url + path, timeout=10) as r:
            for line in r:
                if line.strip():
                    events.append(json.loads(line))
        got[key] = events

    threads = [
        threading.Thread(target=consume, args=(V1, "v1")),
        threading.Thread(target=consume, args=(WORK_V1ALPHA2, "v2")),
    ]
    for t in threads:
        t.start()
    time.sleep(0.3)
    cp.apply(WORK_V2_MANIFEST)
    for t in threads:
        t.join(timeout=10)
    (v1_add,) = [e for e in got["v1"] if e["type"] == "ADDED"]
    assert v1_add["object"]["spec"]["suspendDispatching"] is True
    (v2_add,) = [e for e in got["v2"] if e["type"] == "ADDED"]
    assert v2_add["object"]["apiVersion"] == WORK_V1ALPHA2
    assert v2_add["object"]["spec"]["suspend"] is True


def test_apply_served_version_over_http(served_plane):
    """A write AT a served version converts up to storage on ingress
    (POST /api/apply with a v1alpha2 Work)."""
    cp, url = served_plane
    out = post_json(url, "/api/apply", WORK_V2_MANIFEST)
    assert out  # applied manifest echoed back
    stored = cp.store.get("Work", "karmada-es-m1", "w1")
    assert stored.spec.suspend_dispatching is True


def test_apply_rejects_unserved_version_instead_of_dropping_fields():
    """A write at an unserved version must error, not silently decode the
    storage schema and lose the version-specific fields."""
    cp = ControlPlane()
    bad = dict(WORK_V2_MANIFEST, apiVersion="work.karmada.io/v9")
    with pytest.raises(ValueError, match="not served"):
        cp.apply(bad)


def test_watch_rejects_unserved_version_with_400(served_plane):
    """Bad version params must fail the REQUEST — the conversion runs on
    store writer threads, where a late KeyError would break writes."""
    _, url = served_plane
    with pytest.raises(urllib.error.HTTPError) as ei:
        get_json(url, "/api-watch/Work?timeout=1&version=work.karmada.io/v9")
    assert ei.value.code == 400


def test_api_discovery_lists_served_versions(served_plane):
    """GET /apis (the aggregated apiserver's discovery root): every kind
    with its storage + served versions."""
    from karmada_tpu.models.conversion import BINDING_V1ALPHA1

    _, url = served_plane
    apis = get_json(url, "/apis")
    assert apis["Work"]["storageVersion"] == V1
    assert set(apis["Work"]["servedVersions"]) == {V1, WORK_V1ALPHA2}
    assert BINDING_V1ALPHA1 in apis["ResourceBinding"]["servedVersions"]
    assert apis["Cluster"]["servedVersions"] == [
        apis["Cluster"]["storageVersion"]]


def test_convert_endpoint_over_http(served_plane):
    _, url = served_plane
    out = post_json(url, "/convert", {
        "desiredAPIVersion": V1, "objects": [WORK_V2_MANIFEST]})
    assert out["objects"][0]["spec"]["suspendDispatching"] is True
    back = post_json(url, "/convert", {
        "desiredAPIVersion": WORK_V1ALPHA2, "objects": out["objects"]})
    assert back["objects"][0]["spec"]["suspend"] is True


def test_cli_api_resources_remote(served_plane, capsys):
    from karmada_tpu.cli import main

    _, url = served_plane
    assert main(["--server", url, "api-resources"]) == 0
    out = capsys.readouterr().out
    assert "VERSIONS" in out
    assert "work.karmada.io/v1alpha2" in out  # Work's extra served version


def test_cli_get_at_served_version(served_plane, capsys):
    """karmadactl get --server --api-version: the CLI read half of
    multi-version serving."""
    from karmada_tpu.cli import main

    cp, url = served_plane
    cp.apply(WORK_V2_MANIFEST)
    assert main(["--server", url, "get", "Work", "w1", "-n", "karmada-es-m1",
                 "-o", "json", "--api-version", WORK_V1ALPHA2]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["apiVersion"] == WORK_V1ALPHA2
    assert out["spec"]["suspend"] is True
