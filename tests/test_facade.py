"""Facade-plane tests (karmada_tpu/facade + the estimator wire tier).

Covers the ISSUE-17 acceptance legs compressed for tier-1:

  * wire-drift fixtures: every facade message dataclass round-trips
    to/from_json with seeded non-default values, and the camelCase wire
    keys are pinned so a field rename cannot silently fork the format;
  * wire hardening: an oversize length prefix surfaces as
    EstimatorMalformed (never a hang or Unreachable), a stalled peer as
    EstimatorTimeout — both through the FacadeClient's typed path;
  * server-side coalescing: concurrent AssignReplicas callers share ONE
    batch id / trace id, the coalesce ratio exceeds 1, and each caller
    gets a FacadeAssigned ledger event;
  * what-if capacity planning: placement / headroom (exact capacity) /
    cluster-loss (worst-loss ranking) against a copy-on-write fork —
    and the whatif soak scenario leaves live placements bit-identical
    to a control run with the queries stripped;
  * chaos: estimator.rpc faults fired at the facade transport classify
    typed, the breaker opens and half-open-recovers, and a soak
    hammered by a chaos-faulted facade client keeps the SafetyAuditor
    clean (the facade never writes, so nothing can be lost or
    double-placed).
"""

from __future__ import annotations

import dataclasses
import json
import socket
import struct
import threading
import urllib.request

import pytest

from karmada_tpu import chaos, facade
from karmada_tpu.estimator import wire
from karmada_tpu.estimator.client import (
    CircuitBreaker,
    EstimatorCircuitOpen,
    EstimatorError,
    EstimatorMalformed,
    EstimatorTimeout,
    EstimatorUnreachable,
)
from karmada_tpu.facade import FacadeClient, FacadeService
from karmada_tpu.facade import whatif as whatif_mod
from karmada_tpu.facade.messages import (
    FACADE_METHODS,
    FACADE_RESPONSES,
    WhatIfRequest,
)
from karmada_tpu.loadgen import (
    LoadDriver,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    get_scenario,
)
from karmada_tpu.loadgen.driver import build_binding, build_cluster
from karmada_tpu.models.work import (
    ReplicaRequirements,
    ResourceBinding,
    TargetCluster,
)
from karmada_tpu.utils.quantity import Quantity
from karmada_tpu.obs import events as obs_events

pytestmark = pytest.mark.facade


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    chaos.disarm()
    facade.set_active(None)


def _slice(name="steady"):
    scenario = get_scenario(name)
    clock = VirtualClock()
    return ServeSlice(scenario, clock, ServiceModel()), scenario, clock


def _service(plane, **kw):
    kw.setdefault("batch_window", 8)
    kw.setdefault("batch_deadline_s", 0.05)
    return FacadeService(plane.scheduler, plane.store, **kw)


def _assign_req(name="caller", replicas=2, cpu="500m"):
    return wire.AssignReplicasRequest(
        namespace="facade-test", name=name, replicas=replicas,
        resource_request={"cpu": cpu}, divided=True)


# ---------------------------------------------------------------------------
# wire-drift fixtures: seeded round-trips over every facade message
# ---------------------------------------------------------------------------

# one seeded, every-field-non-default instance per message class; the
# round-trip plus the pinned camelCase keys make a silent wire fork fail
_SEEDED = {
    "SelectClustersRequest": dict(
        namespace="ns7", name="web", resource_request={"cpu": "750m"},
        cluster_names=["m1", "m2"]),
    "SelectClustersResponse": dict(
        clusters=["m1"], excluded={"m2": "insufficient cpu"}),
    "AssignReplicasRequest": dict(
        namespace="ns7", name="api", replicas=13,
        resource_request={"cpu": "250m", "memory": "1Gi"},
        divided=True, cluster_names=["m3"]),
    "AssignReplicasResponse": dict(
        assignments=[{"cluster": "m3", "replicas": 13}],
        outcome="scheduled", message="ok", trace_id="abc123",
        batch_id=7, batch_size=3),
    "WhatIfRequest": dict(
        query="headroom", replicas=64, resource_request={"cpu": "2000m"},
        divided=False, cluster="m1", limit=17),
    "WhatIfResponse": dict(
        query="cluster-loss", source="resident",
        result={"worst": "m1", "ranking": []}),
}

_WIRE_KEYS = {
    "AssignReplicasRequest": {"resourceRequest", "clusterNames"},
    "AssignReplicasResponse": {"traceId", "batchId", "batchSize"},
    "SelectClustersRequest": {"resourceRequest", "clusterNames"},
    "WhatIfRequest": {"resourceRequest"},
}


@pytest.mark.parametrize("cls", sorted(
    {c for c in (*FACADE_METHODS.values(), *FACADE_RESPONSES.values())},
    key=lambda c: c.__name__), ids=lambda c: c.__name__)
def test_wire_drift_round_trip(cls):
    seeded = _SEEDED[cls.__name__]
    msg = cls(**seeded)
    payload = msg.to_json()
    # the wire payload must be pure JSON (no dataclasses leaking through)
    rehydrated = cls.from_json(json.loads(json.dumps(payload)))
    assert rehydrated == msg
    # defaults must also survive (an absent optional key cannot crash)
    assert cls.from_json({}) == cls()
    for key in _WIRE_KEYS.get(cls.__name__, ()):
        assert key in payload, f"wire key {key} missing from {cls.__name__}"


def test_method_registry_covers_dispatch():
    """FACADE_METHODS/FACADE_RESPONSES agree with FacadeService.dispatch:
    a verb added to one but not the other is drift."""
    assert set(FACADE_METHODS) == set(FACADE_RESPONSES) == {
        "SelectClusters", "AssignReplicas", "WhatIf"}


# ---------------------------------------------------------------------------
# wire hardening: oversize frames + stalled peers, typed
# ---------------------------------------------------------------------------


def _raw_server(behave):
    """A one-connection TCP server running `behave(conn)` on a thread;
    returns (host, port, thread)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def run():
        conn, _ = srv.accept()
        try:
            behave(conn)
        finally:
            conn.close()
            srv.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return srv.getsockname()


def test_oversize_frame_is_typed_malformed():
    """A hostile/desynced length prefix above MAX_FRAME_BYTES must
    surface as EstimatorMalformed (a protocol fault, not an outage) and
    drop the connection — never attempt a 4GiB read."""
    def behave(conn):
        conn.recv(1 << 16)  # swallow the request frame
        conn.sendall(struct.pack(">I", wire.MAX_FRAME_BYTES + 1))

    host, port = _raw_server(behave)
    transport = wire.TcpTransport(host, port, timeout=5.0)
    client = FacadeClient(transport, retry_attempts=1,
                          sleep=lambda s: None)
    with pytest.raises(EstimatorMalformed):
        client.assign_replicas(_assign_req())
    assert transport._sock is None  # noqa: SLF001 — connection dropped


def test_stalled_peer_is_typed_timeout():
    """A peer that accepts but never answers must surface as
    EstimatorTimeout within the socket deadline, not hang the caller
    (the breaker needs to SEE the fault to open)."""
    stall = threading.Event()

    def behave(conn):
        conn.recv(1 << 16)
        stall.wait(5.0)  # never respond within the client timeout

    host, port = _raw_server(behave)
    client = FacadeClient(wire.TcpTransport(host, port, timeout=0.2),
                          retry_attempts=1, sleep=lambda s: None)
    try:
        with pytest.raises(EstimatorTimeout):
            client.assign_replicas(_assign_req())
    finally:
        stall.set()


def test_unknown_method_is_an_error_frame():
    """An unknown verb serializes as an error frame, keeping the
    connection usable — not a dropped socket."""
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        host, port = svc.serve()
        transport = wire.TcpTransport(host, port, timeout=5.0)
        with pytest.raises(RuntimeError, match="unknown facade method"):
            transport.call("Bogus", {})
        # same connection still serves real verbs afterwards
        body = transport.call("SelectClusters",
                              wire.SelectClustersRequest().to_json())
        assert wire.SelectClustersResponse.from_json(body).clusters
        transport.close()
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# server-side coalescing
# ---------------------------------------------------------------------------


def test_concurrent_callers_coalesce_into_one_dispatch():
    plane, _, _ = _slice()
    svc = _service(plane, batch_window=8, batch_deadline_s=0.25)
    obs_events.configure()  # fresh, armed ledger for the demux events
    try:
        results = [None] * 6
        barrier = threading.Barrier(6)

        def call(i):
            barrier.wait(timeout=5)
            results[i] = svc.assign(_assign_req(name=f"caller-{i}"))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert all(r is not None for r in results)
        assert all(r.outcome == "scheduled" for r in results)
        assert all(sum(a["replicas"] for a in r.assignments) == 2
                   for r in results)
        # every caller rode the SAME coalesced dispatch
        assert len({r.batch_id for r in results}) == 1
        assert all(r.batch_size == 6 for r in results)
        state = svc.state_payload()
        assert state["calls"] == 6
        assert state["batches"] == 1
        assert state["coalesce_ratio"] == 6.0
        # per-caller ledger events carry the batch's identity
        timeline = obs_events.timeline_payload("facade-test", "caller-0")
        reasons = [e["reason"] for e in timeline["events"]]
        assert obs_events.REASON_FACADE_ASSIGNED in reasons
    finally:
        svc.close()


def test_facade_never_writes_the_store():
    """The facade is a solver service, not a second writer: a burst of
    assigns + what-ifs leaves the store's binding population untouched."""
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        before = plane.store.counts_by_kind()
        svc.assign(_assign_req())
        svc.select_clusters(wire.SelectClustersRequest(
            resource_request={"cpu": "100m"}))
        svc.whatif(WhatIfRequest(query="placement", replicas=4,
                                 resource_request={"cpu": "500m"}))
        assert plane.store.counts_by_kind() == before
    finally:
        svc.close()


def test_select_clusters_excludes_with_diagnosis():
    """SelectClusters is the reference's group+filter phase: an
    affinity allowlist excludes the rest WITH a per-cluster diagnosis
    (capacity pricing belongs to AssignReplicas, not this verb)."""
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        resp = svc.select_clusters(wire.SelectClustersRequest(
            resource_request={"cpu": "500m"},
            cluster_names=["lg-m0", "lg-m1"]))
        assert resp.clusters == ["lg-m0", "lg-m1"]
        assert set(resp.excluded) == {f"lg-m{i}" for i in range(2, 6)}
        assert all("affinity" in why for why in resp.excluded.values())
        fit = svc.select_clusters(wire.SelectClustersRequest(
            resource_request={"cpu": "500m"}))
        assert len(fit.clusters) == 6 and fit.excluded == {}
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# the what-if plane
# ---------------------------------------------------------------------------


def test_whatif_placement_and_unknown_query():
    plane, _, _ = _slice()
    resp = whatif_mod.run_query(
        plane.scheduler, plane.store,
        WhatIfRequest(query="placement", replicas=10,
                      resource_request={"cpu": "1000m"}))
    assert resp.source == "store"
    assert resp.result["outcome"] == "scheduled"
    assert sum(a["replicas"] for a in resp.result["assignments"]) == 10
    with pytest.raises(ValueError, match="unknown what-if query"):
        whatif_mod.run_query(plane.scheduler, plane.store,
                             WhatIfRequest(query="bogus"))


def test_whatif_headroom_finds_exact_capacity():
    """6 loadgen clusters x 64 CPU = 384 one-cpu replicas; the bisection
    must land exactly there, within the probe budget."""
    plane, _, _ = _slice()
    resp = whatif_mod.run_query(
        plane.scheduler, plane.store,
        WhatIfRequest(query="headroom", replicas=1,
                      resource_request={"cpu": "1000m"}))
    res = resp.result
    assert res["max_replicas"] == 384
    assert res["probes"] <= 2 * whatif_mod.HEADROOM_MAX_PROBES
    assert sum(a["replicas"] for a in res["assignments"]) == 384


def test_whatif_cluster_loss_ranks_the_stranding_loss():
    """A binding whose replicas only fit the big cluster strands when
    that cluster is lost; a re-placeable binding strands nothing."""
    plane, _, _ = _slice()
    store = plane.store
    store.create(build_cluster("big", cpu_milli=512_000))
    # 500 one-cpu replicas only fit the 512-CPU cluster; the 6x64-CPU
    # survivors top out at 384, so losing "big" strands all 500
    hostage = build_binding("hostage", replicas=500, divided=True)
    hostage.spec.replica_requirements = ReplicaRequirements(
        resource_request={"cpu": Quantity.parse("1000m")})
    hostage.spec.clusters = [TargetCluster(name="big", replicas=500)]
    store.create(hostage)
    movable = build_binding("movable", replicas=4, divided=True)
    movable.spec.replica_requirements = ReplicaRequirements(
        resource_request={"cpu": Quantity.parse("1000m")})
    movable.spec.clusters = [TargetCluster(name="lg-m0", replicas=4)]
    store.create(movable)
    resp = whatif_mod.run_query(plane.scheduler, plane.store,
                                WhatIfRequest(query="cluster-loss"))
    res = resp.result
    assert resp.source == "store"
    assert res["worst"] == "big"
    by_name = {r["cluster"]: r for r in res["ranking"]}
    assert by_name["big"]["stranded_bindings"] == 1
    assert by_name["big"]["stranded_replicas"] == 500
    assert by_name["lg-m0"]["stranded_bindings"] == 0


@pytest.mark.soak
def test_whatif_soak_leaves_placements_bit_identical():
    """The headline isolation proof: the whatif scenario (capacity
    queries riding a steady soak) must end with the EXACT placement map
    of a control run with the queries stripped."""
    def placements(name_events):
        scenario = get_scenario("whatif")
        if name_events == "control":
            scenario = dataclasses.replace(scenario, events=())
        clock = VirtualClock()
        model = ServiceModel()
        plane = ServeSlice(scenario, clock, model)
        driver = LoadDriver(plane, scenario, clock=clock, model=model,
                            seed=7)
        driver.run()
        placed = {}
        for rb in plane.store.list(ResourceBinding.KIND):
            placed[(rb.metadata.namespace, rb.metadata.name)] = tuple(
                sorted((t.name, t.replicas) for t in rb.spec.clusters))
        return placed, driver

    with_queries, driver = placements("whatif")
    control, _ = placements("control")
    assert with_queries == control
    # and the queries actually ran and answered
    assert [r["query"] for r in driver.whatif_results] == [
        "placement", "headroom", "cluster-loss", "placement", "headroom"]
    assert all(r["result"] for r in driver.whatif_results)


# ---------------------------------------------------------------------------
# chaos at the facade transport
# ---------------------------------------------------------------------------


def _local_client(svc, **kw):
    kw.setdefault("retry_attempts", 1)
    kw.setdefault("sleep", lambda s: None)
    return FacadeClient(wire.LocalTransport(svc.dispatch), **kw)


def test_chaos_modes_classify_typed_at_the_facade():
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        client = _local_client(svc)
        chaos.configure("estimator.rpc:error#1", seed=0)
        with pytest.raises(EstimatorUnreachable):
            client.assign_replicas(_assign_req())
        chaos.configure("estimator.rpc:timeout#1", seed=0)
        with pytest.raises(EstimatorTimeout):
            client.assign_replicas(_assign_req())
        chaos.configure("estimator.rpc:garbage#1", seed=0)
        with pytest.raises(EstimatorMalformed):
            client.assign_replicas(_assign_req())
        slept = []
        slow_client = _local_client(svc, sleep=slept.append)
        chaos.configure("estimator.rpc:slow:0.5#1", seed=0)
        resp = slow_client.assign_replicas(_assign_req())
        assert resp.outcome == "scheduled" and slept == [0.5]
        chaos.disarm()
        assert client.assign_replicas(_assign_req()).outcome == "scheduled"
    finally:
        svc.close()


def test_breaker_opens_and_half_open_recovers_at_the_facade():
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        now = [0.0]
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=10.0,
                                 clock=lambda: now[0])
        client = _local_client(svc, breaker=breaker)
        chaos.configure("estimator.rpc:error#2", seed=0)
        for _ in range(2):
            with pytest.raises(EstimatorUnreachable):
                client.assign_replicas(_assign_req())
        # circuit open: short-circuits without touching the transport
        with pytest.raises(EstimatorCircuitOpen):
            client.assign_replicas(_assign_req())
        # after the reset window one half-open probe flies; the fault
        # budget is exhausted, so it succeeds and closes the circuit
        now[0] = 11.0
        assert client.assign_replicas(_assign_req()).outcome == "scheduled"
        assert client.assign_replicas(_assign_req()).outcome == "scheduled"
    finally:
        svc.close()


@pytest.mark.chaos
@pytest.mark.soak
def test_chaos_facade_hammer_keeps_the_auditor_clean():
    """A facade client hammered by estimator.rpc faults DURING a soak:
    the typed errors land on the facade callers only — the safety
    auditor over the live plane stays clean (no binding lost or
    double-placed) and the breaker recovers once the budget is spent.
    Runs with the runtime race detector ARMED: the hammer thread and the
    driver thread exercise facade.state/facade.solve/scheduler.queue
    concurrently, so off-lock mutations or acquisition-order inversions
    surface here as hard failures."""
    from karmada_tpu.analysis import guards
    from karmada_tpu.utils import locks

    was_armed = guards.armed()
    locks.reset_for_tests()
    inv0 = locks._INVERSIONS.total()  # noqa: SLF001
    trips0 = locks._TRIPS.total()  # noqa: SLF001
    guards.arm()
    wd = locks.LockWatchdog(threshold_s=5.0, poll_s=0.2).start()
    scenario = get_scenario("steady")
    clock = VirtualClock()
    model = ServiceModel()
    plane = ServeSlice(scenario, clock, model)
    svc = _service(plane, batch_deadline_s=0.005)
    stop = threading.Event()
    outcomes = {"ok": 0, "typed": 0}

    def hammer():
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=0.01)
        client = _local_client(svc, breaker=breaker)
        while not stop.is_set():
            try:
                resp = client.assign_replicas(_assign_req(replicas=1))
                if resp.outcome == "scheduled":
                    outcomes["ok"] += 1
            except EstimatorError:
                outcomes["typed"] += 1

    try:
        chaos.configure("estimator.rpc:error#4", seed=0)
        baseline = chaos.capture_baseline()
        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        driver = LoadDriver(plane, scenario, clock=clock, model=model,
                            seed=0)
        driver.run()
        stop.set()
        t.join(timeout=10)
        audit = chaos.audit_soak(driver, baseline)
        assert audit["violations"] == [], json.dumps(audit["violations"])
        # the budgeted faults all fired at the facade seam and the
        # client kept answering afterwards
        assert outcomes["typed"] >= 1
        assert outcomes["ok"] >= 1
        final = _local_client(svc).assign_replicas(_assign_req())
        assert final.outcome == "scheduled"
    finally:
        stop.set()
        svc.close()
        wd.stop()
        guards.arm(was_armed)
    assert locks._INVERSIONS.total() - inv0 == 0, (  # noqa: SLF001
        locks.state_payload()["inversions"])
    assert locks._TRIPS.total() - trips0 == 0  # noqa: SLF001


# ---------------------------------------------------------------------------
# surfaces: /debug/facade, /whatif, the CLI verbs
# ---------------------------------------------------------------------------


def test_debug_facade_and_whatif_endpoints():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    srv = ObservabilityServer()
    url = srv.start()
    plane, _, _ = _slice()
    svc = _service(plane)
    try:
        with urllib.request.urlopen(url + "/debug/facade", timeout=5) as r:
            assert json.loads(r.read()) == {"enabled": False}
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url + "/whatif?query=placement",
                                   timeout=5)
        assert exc.value.code == 503
        facade.set_active(svc)
        svc.assign(_assign_req())
        with urllib.request.urlopen(url + "/debug/facade", timeout=5) as r:
            state = json.loads(r.read())
        assert state["enabled"] and state["calls"] == 1
        with urllib.request.urlopen(
                url + "/whatif?query=placement&replicas=3&cpu=500m",
                timeout=30) as r:
            payload = json.loads(r.read())
        assert payload["query"] == "placement"
        assert payload["result"]["outcome"] == "scheduled"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url + "/whatif?query=bogus", timeout=5)
        assert exc.value.code == 400
        assert "unknown what-if query" in json.loads(
            exc.value.read())["error"]
    finally:
        facade.set_active(None)
        svc.close()
        srv.stop()


def test_cli_estimate_and_whatif_verbs(capsys):
    from karmada_tpu.cli import main as cli_main
    from karmada_tpu.utils.httpserve import ObservabilityServer

    plane, _, _ = _slice()
    svc = _service(plane)
    srv = ObservabilityServer()
    url = srv.start()
    try:
        host, port = svc.serve()
        facade.set_active(svc)
        rc = cli_main(["estimate", "--facade-addr", f"{host}:{port}",
                       "--replicas", "3", "--cpu", "500m"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "outcome: scheduled" in out and "3 replicas" in out
        rc = cli_main(["whatif", "--endpoint", url,
                       "--query", "headroom", "--cpu", "1000m"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "384" in out
        rc = cli_main(["whatif", "--endpoint", url, "--query",
                       "cluster-loss", "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0
        assert json.loads(out)["query"] == "cluster-loss"
    finally:
        facade.set_active(None)
        svc.close()
        srv.stop()
