"""Multi-template (multi-component) scheduling: MaxAvailableComponentSets
estimation (general.go:96-160, estimation.go:42-103), device/serial parity,
and the end-to-end FlinkDeployment-style flow through the hook tier."""

import random

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.estimator.server import AccurateEstimatorServer
from karmada_tpu.interpreter.interpreter import (
    Customization,
    OP_INTERPRET_COMPONENT,
)
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
    SPREAD_BY_FIELD_CLUSTER,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    Component,
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.solver import solve
from karmada_tpu.utils.quantity import Quantity


def flink_components(jm_cpu="1", tm_cpu="2", jm_n=1, tm_n=3):
    return [
        Component(name="jobmanager", replicas=jm_n,
                  replica_requirements=ReplicaRequirements(resource_request={
                      "cpu": Quantity.parse(jm_cpu),
                      "memory": Quantity.parse("2Gi")})),
        Component(name="taskmanager", replicas=tm_n,
                  replica_requirements=ReplicaRequirements(resource_request={
                      "cpu": Quantity.parse(tm_cpu),
                      "memory": Quantity.parse("4Gi")})),
    ]


def mk_cluster(name, cpu="64", mem="256Gi", pods=110):
    return Cluster(
        metadata=ObjectMeta(name=name),
        spec=ClusterSpec(),
        status=ClusterStatus(
            api_enablements=[APIEnablement("flink.apache.org/v1beta1",
                                           ["FlinkDeployment"])],
            resource_summary=ResourceSummary(allocatable={
                "cpu": Quantity.parse(cpu), "memory": Quantity.parse(mem),
                "pods": Quantity.parse(str(pods))}),
        ),
    )


def test_general_estimator_component_sets_math():
    est = GeneralEstimator()
    c = mk_cluster("m", cpu="64", mem="256Gi", pods=110)
    comps = flink_components()  # per set: 1x(1cpu,2Gi) + 3x(2cpu,4Gi) = 7cpu, 14Gi, 4 pods
    sets = est._max_sets_for_cluster(c, comps)
    # cpu bound: 64000m // 7000m = 9; mem bound: 256Gi//14Gi = 18; pods: 110//4 = 27
    assert sets == 9

    # pods bound wins when pods are scarce
    c2 = mk_cluster("m2", cpu="64", mem="256Gi", pods=7)
    assert est._max_sets_for_cluster(c2, comps) == 1  # 7 // 4

    # missing allocatable for a requested resource -> 0
    c3 = mk_cluster("m3")
    del c3.status.resource_summary.allocatable["memory"]
    assert est._max_sets_for_cluster(c3, comps) == 0

    # componentless replicas=0 set: allowed pods bound
    assert est._max_sets_for_cluster(c, [Component(name="x", replicas=0)]) == 110


def test_is_multi_template_applicable():
    spec = ResourceBindingSpec(components=flink_components())
    assert not serial.is_multi_template_applicable(spec)  # no placement
    spec.placement = Placement(spread_constraints=[SpreadConstraint(
        spread_by_field=SPREAD_BY_FIELD_CLUSTER, min_groups=1, max_groups=2)])
    assert not serial.is_multi_template_applicable(spec)  # max_groups != 1
    spec.placement.spread_constraints[0].max_groups = 1
    assert serial.is_multi_template_applicable(spec)
    spec.components = spec.components[:1]
    assert not serial.is_multi_template_applicable(spec)  # < 2 components


def _mt_spec(b, uid="u"):
    return ResourceBindingSpec(
        resource=ObjectReference(api_version="flink.apache.org/v1beta1",
                                 kind="FlinkDeployment", namespace="default",
                                 name=f"job-{b}", uid=uid),
        replicas=0,
        components=flink_components(tm_cpu=str(1 + b % 3)),
        placement=Placement(spread_constraints=[SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER, min_groups=1, max_groups=1)]),
    )


def test_multi_template_routes_to_device_and_matches_serial():
    rng = random.Random(3)
    clusters = [
        mk_cluster(f"m{i}", cpu=str(rng.choice([8, 16, 64])),
                   mem=rng.choice(["32Gi", "64Gi", "256Gi"]),
                   pods=rng.choice([10, 110]))
        for i in range(9)
    ]
    items = [(_mt_spec(b, uid=f"uid-{b}"), ResourceBindingStatus())
             for b in range(12)]
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    assert (batch.route == tensors.ROUTE_DEVICE).all()
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    for b, (spec, st) in enumerate(items):
        want = serial.schedule(spec, st, clusters, cal)
        want_map = {tc.name: tc.replicas for tc in want}
        got_map = {tc.name: tc.replicas for tc in got[b]}
        assert got_map == want_map, f"b={b}: serial={want_map} device={got_map}"
        assert len(got_map) == 1  # spread 1..1: exactly one cluster
        assert set(got_map.values()) == {0}  # propagated whole, no division


def test_multi_component_without_single_cluster_constraint_on_device():
    """Non-applicable multi-component shapes (no 1..1 cluster constraint)
    stay on device too: serial estimates them per-replica with nil
    requirements (allowed-pods row) and propagates with replicas 0 — the
    kernel's non_workload path.  Parity across several placement shapes."""
    rng = random.Random(5)
    clusters = [
        mk_cluster(f"m{i}", cpu=str(rng.choice([8, 16, 64])),
                   mem=rng.choice(["32Gi", "64Gi", "256Gi"]),
                   pods=rng.choice([10, 110]))
        for i in range(7)
    ]
    shapes = [
        Placement(),  # no constraints at all
        Placement(spread_constraints=[SpreadConstraint(  # wider than 1..1
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=2, max_groups=4)]),
        Placement(spread_constraints=[SpreadConstraint(  # min 1, max 3
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=1, max_groups=3)]),
    ]
    items = []
    for b in range(9):
        spec = _mt_spec(b, uid=f"uid-{b}")
        spec.placement = shapes[b % len(shapes)]
        items.append((spec, ResourceBindingStatus()))
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    assert (batch.route == tensors.ROUTE_DEVICE).all()
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    for b, (spec, st) in enumerate(items):
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001
            assert isinstance(got[b], type(e)), (b, e, got[b])
            continue
        want_map = {tc.name: tc.replicas for tc in want}
        got_map = {tc.name: tc.replicas for tc in got[b]}
        assert got_map == want_map, f"b={b}: serial={want_map} device={got_map}"


def test_estimator_server_component_sets():
    m = FakeMemberCluster("m", cpu_allocatable_milli=64_000,
                          memory_allocatable_gi=256, pods_allocatable=110)
    srv = AccurateEstimatorServer(m)
    assert srv.max_available_component_sets(flink_components()) == 9


def test_flink_style_e2e_via_component_hook():
    cp = ControlPlane()
    cp.add_member("small", cpu_milli=8_000)
    cp.add_member("big", cpu_milli=64_000)
    for member in cp.members.values():
        member.api_enablements.append(
            APIEnablement("flink.apache.org/v1beta1", ["FlinkDeployment"]))
    cp.tick()

    def get_components(manifest):
        spec = manifest.get("spec", {})
        return [
            Component(name=n, replicas=int(c.get("replicas", 1)),
                      replica_requirements=ReplicaRequirements(resource_request={
                          "cpu": Quantity.parse(str(c.get("cpu", "1")))}))
            for n, c in spec.get("components", {}).items()
        ]

    cp.interpreter.register(Customization(
        api_version="flink.apache.org/v1beta1", kind="FlinkDeployment",
        hooks={OP_INTERPRET_COMPONENT: get_components},
    ))
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="flink-pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="flink.apache.org/v1beta1", kind="FlinkDeployment")],
            placement=Placement(spread_constraints=[SpreadConstraint(
                spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                min_groups=1, max_groups=1)]),
        ),
    ))
    cp.apply({
        "apiVersion": "flink.apache.org/v1beta1", "kind": "FlinkDeployment",
        "metadata": {"name": "wordcount", "namespace": "default"},
        "spec": {"components": {
            "jobmanager": {"replicas": 1, "cpu": "1"},
            "taskmanager": {"replicas": 3, "cpu": "2"},
        }},
    })
    cp.tick()

    rb = cp.store.get(ResourceBinding.KIND, "default", "wordcount-flinkdeployment")
    assert len(rb.spec.components) == 2
    assert rb.spec.replicas == 0
    # single target, and it must be the big cluster (most component sets fit)
    assert [t.name for t in rb.spec.clusters] == ["big"]
    assert rb.spec.clusters[0].replicas == 0
    # whole manifest applied, unrevised
    applied = cp.member("big").get("FlinkDeployment", "default", "wordcount")
    assert applied is not None
    assert applied.manifest["spec"]["components"]["taskmanager"]["replicas"] == 3
    assert cp.member("small").get("FlinkDeployment", "default", "wordcount") is None


# -- node-level set packing (estimator/wire.py, reference estimate.go TODO) --


def test_node_packing_fragmentation_caught():
    """Two 1-cpu nodes cannot host a 2-cpu pod: the pool bound said one
    set fits, node-level packing says zero — the overreport the
    reference's pool-only plugins leave open."""
    from karmada_tpu.estimator.wire import max_sets_from_free_table

    comps = [Component(name="big", replicas=1,
                       replica_requirements=ReplicaRequirements(
                           resource_request={"cpu": Quantity.parse("2")}))]
    free = [{"cpu": 1000, "pods": 10}, {"cpu": 1000, "pods": 10}]
    assert max_sets_from_free_table(free, comps) == 0
    # one node with the same pool total packs the set
    assert max_sets_from_free_table([{"cpu": 2000, "pods": 10}], comps) == 1


def test_node_packing_spreads_replicas_across_nodes():
    """Replicas of one set place independently: 3x 1-cpu replicas fit
    three 1-cpu nodes (set count limited by total, not per-node)."""
    from karmada_tpu.estimator.wire import max_sets_from_free_table

    comps = [Component(name="tm", replicas=3,
                       replica_requirements=ReplicaRequirements(
                           resource_request={"cpu": Quantity.parse("1")}))]
    free = [{"cpu": 1000, "pods": 5}] * 3
    assert max_sets_from_free_table(free, comps) == 1
    free = [{"cpu": 2000, "pods": 5}] * 3
    assert max_sets_from_free_table(free, comps) == 2


def test_node_packing_pods_only_matches_pool():
    """No per-replica resource requests: pods spread freely, the pool
    bound is exact and the packer returns it unchanged."""
    from karmada_tpu.estimator.wire import max_sets_from_free_table

    comps = [Component(name="c", replicas=2)]
    free = [{"pods": 3}, {"pods": 4}]
    assert max_sets_from_free_table(free, comps) == 3  # 7 // 2


def test_node_packing_memory_units():
    """Non-cpu resources compare in milli (request Value x1000), the
    same convention as the pool bound."""
    from karmada_tpu.estimator.wire import max_sets_from_free_table

    comps = [Component(name="m", replicas=1,
                       replica_requirements=ReplicaRequirements(
                           resource_request={"memory": Quantity.parse("2Gi")}))]
    gib = 1 << 30
    free = [{"memory": 3 * gib * 1000, "pods": 10},
            {"memory": 3 * gib * 1000, "pods": 10}]
    # pool: 6Gi -> 3 sets; nodes: each holds ONE 2Gi pod with 1Gi stranded
    assert max_sets_from_free_table(free, comps) == 2
