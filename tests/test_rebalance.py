"""Rebalance plane: detect kernel, pacing, drains, and the closed loop.

Covers ISSUE 10's acceptance surface:
  * the jitted detect kernel's overcommit / spread-divergence math;
  * the shared eviction-pacing budget (property test + the regression
    with BOTH evictors — descheduler and rebalance plane — armed);
  * drain mechanics: graceful eviction tasks with producer=rebalance,
    origin-tagged re-place promotion, conservation audit;
  * the chaos `rebalance.plan` seam (skip + raise containment);
  * the FederatedHPA fast path (scale event -> priority push, one cycle);
  * the compressed virtual-clock hotspot soak: skewed arrivals pack the
    hot clusters, capacity churn overcommits them, the plane drains to
    within threshold with zero conservation violations (policy-path
    injection, so the detector fan-out is under load too);
  * carry-chain parity: rebalance re-solves through the pipelined
    executor (chunked, waves == chunk, carry) vs the serial rebalance
    control, bit-identical;
  * /debug/rebalance + `karmadactl rebalance` smoke.
"""

from __future__ import annotations

import copy
import json
import random
import threading
import urllib.request

import pytest

from karmada_tpu import chaos as chaos_mod
from karmada_tpu import rebalance as rebalance_mod
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.loadgen import (
    LoadDriver,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    get_scenario,
)
from karmada_tpu.loadgen.driver import build_binding, build_cluster
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
)
from karmada_tpu.models.work import (
    GracefulEvictionTask,
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import rebalance_detect, serial, tensors
from karmada_tpu.rebalance import EvictionBudget, RebalanceConfig, RebalancePlane
from karmada_tpu.rebalance import pacing as pacing_mod
from karmada_tpu.rebalance import plane as plane_mod
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.utils.quantity import Quantity

pytestmark = pytest.mark.rebalance

import numpy as np  # noqa: E402


class FakeClock:
    def __init__(self, t=1_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    rebalance_mod.set_active(None)
    chaos_mod.disarm()


# -- detect kernel ------------------------------------------------------------

SPREAD_OFF = 1 << 20  # the plane's report-only sentinel (plane.py)


def test_detect_overcommit_and_saturated():
    need, over, div = rebalance_detect.score(
        np.array([480, 20, 10, 7]), np.array([300, 1000, 1000, 0]),
        np.array([True, True, True, True]), 1000, SPREAD_OFF)
    assert over[0] == 1600 and need[0] == 180
    assert need[1] == 0 and need[2] == 0
    # zero capacity with committed load: the saturated sentinel, and the
    # whole committed count wants draining
    assert over[3] == rebalance_detect.OVER_SATURATED
    assert need[3] == 7


def test_detect_invalid_clusters_never_selected():
    need, over, div = rebalance_detect.score(
        np.array([500]), np.array([100]), np.array([False]), 1000,
        SPREAD_OFF)
    assert need[0] == 0 and over[0] == 0


def test_detect_threshold_scaling():
    # threshold 1500 milli allows 1.5x capacity before draining
    need, _, _ = rebalance_detect.score(
        np.array([140, 160]), np.array([100, 100]),
        np.array([True, True]), 1500, SPREAD_OFF)
    assert need[0] == 0
    assert need[1] == 10


def test_detect_spread_divergence_gate():
    committed = np.array([90, 10, 0, 0])
    capacity = np.array([100, 100, 100, 100])
    valid = np.ones(4, dtype=bool)
    # gate off (tolerance above any possible divergence): report-only
    need0, _, div = rebalance_detect.score(committed, capacity, valid,
                                           2000, 1 << 20)
    assert int(div[0]) == 900 - 250  # share 900m vs fair 250m
    assert (need0 == 0).all()
    # gate at 300 milli: cluster 0 diverges (650 > 300) and drains down
    # to (fair + tol) of the committed total
    need1, _, _ = rebalance_detect.score(committed, capacity, valid,
                                         2000, 300)
    assert int(need1[0]) == 90 - (250 + 300) * 100 // 1000
    assert (need1[1:] == 0).all()


# -- pacing budget ------------------------------------------------------------

def test_budget_property_two_consumers_never_exceed():
    """Random interleaving of two consumers: grants per cluster per
    window never exceed per_cluster, regardless of who asks."""
    clock = FakeClock()
    budget = EvictionBudget(per_cluster=5, interval_s=10.0, clock=clock)
    rng = random.Random(42)
    grants = {"m1": 0, "m2": 0}
    for _ in range(200):
        cluster = rng.choice(["m1", "m2"])
        consumer = rng.choice(["descheduler", "rebalance"])
        if budget.try_acquire(cluster, consumer=consumer):
            grants[cluster] += 1
    assert grants["m1"] <= 5 and grants["m2"] <= 5
    # window rolls: fresh allowance
    clock.advance(10.0)
    assert budget.try_acquire("m1")
    assert budget.remaining("m1") == 4


def test_budget_denials_counted_by_consumer():
    clock = FakeClock()
    budget = EvictionBudget(per_cluster=1, interval_s=10.0, clock=clock)
    base = pacing_mod.BUDGET_DENIED.value(consumer="descheduler")
    assert budget.try_acquire("m1", consumer="rebalance")
    assert not budget.try_acquire("m1", consumer="descheduler")
    assert pacing_mod.BUDGET_DENIED.value(consumer="descheduler") == base + 1


# -- plane unit mechanics -----------------------------------------------------

class _SchedStub:
    """The slice of Scheduler the plane touches: a queue clock + promote."""

    def __init__(self, clock):
        self.queue = type("Q", (), {"now": staticmethod(clock)})()
        self.promoted = []

    def promote(self, key, priority=0, origin="rebalance"):
        self.promoted.append((key, priority, origin))
        return "admitted"


def _divided_binding(name, targets, replicas=None, namespace="ns"):
    rb = ResourceBinding()
    rb.metadata.namespace = namespace
    rb.metadata.name = name
    total = sum(r for _, r in targets)
    rb.spec = ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 namespace=namespace, name=name,
                                 uid=f"uid-{name}"),
        replicas=replicas if replicas is not None else total,
        placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED)),
        clusters=[TargetCluster(name=c, replicas=r) for c, r in targets],
    )
    return rb


def _plane_env(per_cluster=8, pods=100):
    clock = FakeClock()
    store = ObjectStore()
    store.create(build_cluster("m1", pods=pods))
    store.create(build_cluster("m2", pods=pods))
    sched = _SchedStub(clock)
    budget = EvictionBudget(per_cluster=per_cluster, interval_s=60.0,
                            clock=clock)
    plane = RebalancePlane(store, sched,
                           cfg=RebalanceConfig(interval_s=5.0),
                           budget=budget, clock=clock)
    return clock, store, sched, plane


def test_drain_evicts_gracefully_and_promotes():
    _, store, sched, plane = _plane_env()
    # 40 replicas committed on m1 (capacity 100): fine.  Crush m1's
    # allocatable to 20 pods -> overcommitted, drain_need 20.
    for i in range(4):
        store.create(_divided_binding(f"b{i}", [("m1", 10)]))

    def crush(c: Cluster) -> None:
        c.status.resource_summary.allocatable["pods"] = Quantity.parse("20")

    store.mutate(Cluster.KIND, "", "m1", crush)
    snap = plane.run_cycle()
    assert snap["clusters"]["m1"]["drain_need"] == 0 or snap["evicted"] > 0
    assert snap["evicted"] == 2  # 2 x 10 replicas covers the need of 20
    drained = [rb for rb in store.list(ResourceBinding.KIND)
               if rb.spec.graceful_eviction_tasks]
    assert len(drained) == 2
    for rb in drained:
        task = rb.spec.graceful_eviction_tasks[0]
        assert task.producer == "rebalance"
        assert task.from_cluster == "m1"
        assert task.replicas == 10
        assert not rb.spec.clusters  # the allotment left spec.clusters
    assert len(sched.promoted) == 2
    assert all(origin == "rebalance" for _, _, origin in sched.promoted)
    # conservation holds mid-drain: clusters + tasks == desired
    assert snap["violations"] == 0
    # an in-flight drain is not drained again next cycle
    snap2 = plane.run_cycle()
    assert snap2["evicted"] <= 2  # remaining need only, never the same rbs
    for rb in store.list(ResourceBinding.KIND):
        assert len([t for t in rb.spec.graceful_eviction_tasks
                    if t.producer == "rebalance"]) <= 1


def test_drain_respects_budget_pacing():
    _, store, sched, plane = _plane_env(per_cluster=3)
    for i in range(20):
        store.create(_divided_binding(f"b{i}", [("m1", 10)]))

    def crush(c: Cluster) -> None:
        c.status.resource_summary.allocatable["pods"] = Quantity.parse("10")

    store.mutate(Cluster.KIND, "", "m1", crush)
    snap = plane.run_cycle()
    assert snap["evicted"] == 3, "the per-cluster window caps the drain"
    # same window: nothing left to grant
    snap2 = plane.run_cycle()
    assert snap2["evicted"] == 0


def test_conservation_violation_detected():
    _, store, _, plane = _plane_env()
    rb = _divided_binding("hurt", [("m1", 2)], replicas=5)
    rb.spec.graceful_eviction_tasks.append(GracefulEvictionTask(
        from_cluster="m2", replicas=1, producer="rebalance"))
    store.create(rb)  # serving 3 < desired 5
    base = plane_mod.CONSERVATION_VIOLATIONS.total()
    snap = plane.run_cycle()
    assert snap["violations"] == 1
    assert plane_mod.CONSERVATION_VIOLATIONS.total() == base + 1
    assert plane.stats()["violation_samples"][-1]["binding"] == "ns/hurt"


def test_duplicated_bindings_never_drained():
    _, store, sched, plane = _plane_env()
    rb = ResourceBinding()
    rb.metadata.namespace = "ns"
    rb.metadata.name = "dup"
    rb.spec = ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 namespace="ns", name="dup", uid="u"),
        replicas=1,
        placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        clusters=[TargetCluster(name="m1", replicas=200)],
    )
    store.create(rb)

    def crush(c: Cluster) -> None:
        c.status.resource_summary.allocatable["pods"] = Quantity.parse("10")

    store.mutate(Cluster.KIND, "", "m1", crush)
    snap = plane.run_cycle()
    assert snap["clusters"]["m1"]["drain_need"] > 0
    assert snap["evicted"] == 0 and not sched.promoted


def test_chaos_plan_seam_skip_and_raise():
    clock, store, sched, plane = _plane_env()
    chaos_mod.configure("rebalance.plan:skip#1")
    base = plane_mod.CYCLE_FAULTS.total()
    assert plane.run_cycle() == {"skipped": "chaos"}
    assert plane_mod.CYCLE_FAULTS.value(kind="chaos_skip") >= 1
    assert plane_mod.CYCLE_FAULTS.total() == base + 1
    # raise mode: maybe_run contains it (counted, never propagated)
    chaos_mod.configure("rebalance.plan:raise#1")
    plane._last_run = float("-inf")
    plane.maybe_run()  # must not raise
    assert plane_mod.CYCLE_FAULTS.value(kind="RuntimeError") >= 1
    chaos_mod.disarm()


# -- shared budget with BOTH evictors armed ----------------------------------

def test_descheduler_and_rebalance_share_one_budget():
    """Regression for the stampede: with the descheduler and the
    rebalance plane armed on one plane, combined evictions against a
    cluster inside one pacing window never exceed the shared budget."""
    from karmada_tpu.controllers.descheduler import Descheduler
    from karmada_tpu.store.worker import Runtime

    clock = FakeClock()
    store = ObjectStore()
    store.create(build_cluster("m1", pods=100))
    store.create(build_cluster("m2", pods=100))
    budget = EvictionBudget(per_cluster=4, interval_s=60.0, clock=clock)
    sched = _SchedStub(clock)
    plane = RebalancePlane(store, sched,
                           cfg=RebalanceConfig(interval_s=5.0),
                           budget=budget, clock=clock)

    class _Member:
        healthy = True

        def unschedulable_replicas(self, *a):
            return 1  # every binding always has one stuck replica

    runtime = Runtime()
    desched = Descheduler(store, runtime, {"m1": _Member(), "m2": _Member()},
                          budget=budget)
    for i in range(12):
        rb = _divided_binding(f"b{i}", [("m1", 10)])
        rb.spec.placement.replica_scheduling.replica_division_preference = (
            REPLICA_DIVISION_AGGREGATED)
        store.create(rb)

    def crush(c: Cluster) -> None:
        c.status.resource_summary.allocatable["pods"] = Quantity.parse("10")

    store.mutate(Cluster.KIND, "", "m1", crush)
    # descheduler round first: its per-binding m1 shrinks draw tokens
    desched.run_once()
    shrunk = sum(1 for rb in store.list(ResourceBinding.KIND)
                 if sum(t.replicas for t in rb.spec.clusters) < 10
                 and not rb.spec.graceful_eviction_tasks)
    assert shrunk == 4, "descheduler capped by the shared budget"
    # same window: the rebalance plane finds the budget spent
    snap = plane.run_cycle()
    assert snap["evicted"] == 0, \
        "rebalance must not stampede m1 after the descheduler spent it"
    # next window: the plane drains
    clock.advance(60.0)
    snap2 = plane.run_cycle()
    assert 0 < snap2["evicted"] <= 4


# -- FederatedHPA fast path ---------------------------------------------------

def test_hpa_scale_event_fast_path_priority_push():
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.scheduler import metrics as sched_metrics

    clock = FakeClock(1_000_000.0)
    cp = ControlPlane(backend="serial", clock=clock)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    from karmada_tpu.models.autoscaling import (
        CrossVersionObjectReference,
        FederatedHPA,
        FederatedHPASpec,
        MetricSpec,
        MetricTarget,
        ResourceMetricSource,
    )
    from karmada_tpu.models.policy import PropagationPolicy, PropagationSpec
    from karmada_tpu.models.policy import ResourceSelector

    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))),
        )))
    cp.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"replicas": 4, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}}},
    })
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "web-deployment")
    assert sum(t.replicas for t in rb.spec.clusters) == 4
    for m in cp.members.values():
        m.set_load("Deployment", "default", "web", {"cpu": 90})
    cp.store.create(FederatedHPA(
        metadata=ObjectMeta(name="web-hpa", namespace="default"),
        spec=FederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                api_version="apps/v1", kind="Deployment", name="web"),
            min_replicas=2, max_replicas=10,
            metrics=[MetricSpec(resource=ResourceMetricSource(
                name="cpu",
                target=MetricTarget(type="Utilization",
                                    average_utilization=50)))])))
    base = sched_metrics.PRIORITY_PUSHES.value(origin="hpa")
    cp.tick()
    # the scale event took the fast path: priority push counted, the
    # binding's replicas follow the scale, and the scheduler re-placed
    assert sched_metrics.PRIORITY_PUSHES.value(origin="hpa") > base
    rb = cp.store.get(ResourceBinding.KIND, "default", "web-deployment")
    want = int(cp.store.get("Deployment", "default", "web")
               .manifest["spec"]["replicas"])
    assert want > 4
    assert rb.spec.replicas == want
    assert sum(t.replicas for t in rb.spec.clusters) == want


# -- the compressed hotspot soak ---------------------------------------------

def test_hotspot_soak_drains_to_threshold_conserving():
    """hotspot -> drain -> re-place -> converge on the virtual clock:
    skewed arrivals pack the hot clusters through the POLICY PATH (the
    detector renders every binding), capacity churn overcommits them,
    and the rebalance plane must drain back inside the threshold with
    zero conservation violations and every binding scheduled."""
    sc = get_scenario("hotspot")
    assert sc.policy_path and sc.binding_style == "divided"
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(sc, clock, model, backend="serial")
    driver = LoadDriver(plane, sc, clock=clock, model=model, seed=3)
    payload = driver.run()
    assert payload["injected"] == payload["scheduled"]
    reb = payload["rebalance"]
    assert reb["enabled"] and reb["evictions"] > 0
    assert reb["conservation_violations"] == 0
    last = reb["last"]
    assert last["converged"]
    thr = reb["config"]["overcommit_threshold_milli"]
    for name, row in last["clusters"].items():
        if row["capacity"] > 0:
            assert row["over_milli"] <= thr, (name, row)
    # the peak proves there WAS an overcommit episode to drain
    assert max(reb["peak_over_milli"].values()) > thr
    # the chaos rebalance.plan:skip fault fired and the auditor is clean
    audit = payload["safety_audit"]
    assert audit["violations"] == []
    assert payload["chaos"]["fired_by_site"].get("rebalance.plan") == 1
    # every drain settled (graceful tasks gone) and nothing is parked
    assert sum(payload["residual_queue"].values()) == 0
    for rb in plane.store.list(ResourceBinding.KIND):
        assert not rb.spec.graceful_eviction_tasks


def test_hotspot_soak_deterministic():
    sc = get_scenario("hotspot")
    outs = []
    for _ in range(2):
        model = ServiceModel()
        clock = VirtualClock()
        plane = ServeSlice(sc, clock, model, backend="serial")
        driver = LoadDriver(plane, sc, clock=clock, model=model, seed=7)
        payload = driver.run()
        outs.append((payload["rebalance"]["evictions"],
                     payload["rebalance"]["last"]["clusters"],
                     payload["injected"], payload["scheduled"]))
    assert outs[0] == outs[1]


# -- carry-chain parity of rebalance re-solves --------------------------------

def _parity_clusters(n=8):
    out = []
    rng = random.Random(11)
    for i in range(n):
        c = build_cluster(f"member-{i:02d}",
                          cpu_milli=rng.randint(16_000, 64_000),
                          memory_gi=rng.choice([64, 128, 256]),
                          pods=rng.randint(80, 200))
        out.append(c)
    return out


def _parity_items(names, n=64):
    rng = random.Random(5)
    placements = [
        Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))),
        Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED)),
    ]
    items = []
    for b in range(n):
        # a rebalance re-solve: the binding HAD an assignment; part of it
        # was drained, and the remainder seeds Steady/Fresh modes
        prev_n = rng.randint(1, 3)
        start = rng.randrange(len(names))
        replicas = rng.choice([2, 4, 8, 16])
        prev = [TargetCluster(name=names[(start + j) % len(names)],
                              replicas=max(1, replicas // prev_n))
                for j in range(prev_n)]
        spec = ResourceBindingSpec(
            resource=ObjectReference(api_version="apps/v1",
                                     kind="Deployment",
                                     namespace=f"ns-{b % 8}", name=f"app-{b}",
                                     uid=f"uid-{b}"),
            replicas=replicas,
            replica_requirements=ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(rng.choice([100, 250, 500])),
                "memory": Quantity.from_units(rng.choice([1, 2])),
            }),
            placement=placements[b % len(placements)],
            clusters=prev,
            reschedule_triggered_at=(100.0 if b % 3 == 0 else None),
        )
        items.append((spec, ResourceBindingStatus()))
    return items


def _serial_control(items, clusters):
    """One binding at a time, consuming the positive delta over the
    previous assignment (the wave accumulator's rule —
    tests/test_contention.py pins the equivalence)."""
    clusters = copy.deepcopy(clusters)
    cal = serial.make_cal_available([GeneralEstimator()])
    by_name = {c.metadata.name: c for c in clusters}
    results = []
    for spec, st in items:
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001 — outcome object
            results.append(e)
            continue
        results.append(want)
        prev = {tc.name: tc.replicas for tc in spec.clusters}
        req = spec.replica_requirements.resource_request
        for tc in want:
            delta = max(tc.replicas - prev.get(tc.name, 0), 0)
            if delta == 0:
                continue
            alloc = by_name[tc.name].status.resource_summary.allocated
            alloc["cpu"] = Quantity.from_milli(
                alloc.get("cpu", Quantity(0)).milli
                + delta * req["cpu"].milli)
            alloc["memory"] = Quantity.from_units(
                alloc.get("memory", Quantity(0)).value()
                + delta * req["memory"].value())
            alloc["pods"] = Quantity.from_units(
                alloc.get("pods", Quantity(0)).value() + delta)
    return results


def test_replace_parity_carry_chain_vs_serial_control():
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    clusters = _parity_clusters()
    names = [c.metadata.name for c in clusters]
    items = _parity_items(names, n=64)
    cindex = tensors.ClusterIndex.build(clusters)
    chunk = 16
    res = sched_pipeline.run_pipeline(
        items, cindex, GeneralEstimator(), chunk=chunk, waves=chunk,
        cache=tensors.EncoderCache(), carry=True, carry_spread=True)
    control = _serial_control(items, clusters)
    assert len(res.results) == len(items), "every row must route device"
    for i, want in enumerate(control):
        got = res.results[i]
        if isinstance(want, Exception):
            assert isinstance(got, type(want)), (i, want, got)
            continue
        wm = {tc.name: tc.replicas for tc in want}
        gm = {tc.name: tc.replicas for tc in got}
        assert gm == wm, (i, wm, gm)


# -- exposure smoke -----------------------------------------------------------

def test_debug_rebalance_http_and_cli(capsys):
    from karmada_tpu.cli import main as cli_main
    from karmada_tpu.utils.httpserve import ObservabilityServer

    clock = FakeClock()
    store = ObjectStore()
    store.create(build_cluster("m1", pods=50))
    store.create(_divided_binding("b0", [("m1", 80)]))
    sched = _SchedStub(clock)
    plane = RebalancePlane(store, sched,
                           cfg=RebalanceConfig(interval_s=5.0), clock=clock)
    rebalance_mod.set_active(plane)
    plane.run_cycle()
    srv = ObservabilityServer(store=store)
    url = srv.start(port=0)
    try:
        with urllib.request.urlopen(url + "/debug/rebalance") as r:
            state = json.loads(r.read().decode())
        assert state["enabled"] and state["cycles"] == 1
        assert state["last"]["clusters"]["m1"]["over_milli"] == 1600
        rc = cli_main(["rebalance", "--endpoint", url])
        assert rc == 0
        out = capsys.readouterr().out
        assert "rebalance plane" in out and "m1" in out
    finally:
        srv.stop()
    # disarmed payload for dashboards
    rebalance_mod.set_active(None)
    assert rebalance_mod.state_payload() == {"enabled": False}
    assert "no rebalance plane" in rebalance_mod.render_state(
        {"enabled": False})


def test_scheduler_promote_tags_origin():
    """promote() pushes through the admission gate with the caller's
    origin; the queue buckets the dwell by it at pop."""
    from karmada_tpu.scheduler.queue import SchedulingQueue

    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    q.push(("ns", "a"), 0, origin="rebalance")
    clock.advance(1.0)
    infos = q.pop_ready()
    assert len(infos) == 1 and infos[0].origin == "rebalance"
