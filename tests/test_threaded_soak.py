"""Opt-in threaded chaos soak (serve-mode threads + live churn).

Run with KARMADA_TPU_SOAK=1 (takes ~2 minutes); the fast deterministic
variant lives in tests/test_chaos_convergence.py. This harness found the
round-3 flap-storm wedge that motivated the tolerationSeconds work.
"""

from __future__ import annotations

import os
import random
import time

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding

pytestmark = pytest.mark.skipif(
    os.environ.get("KARMADA_TPU_SOAK") != "1",
    reason="threaded soak is opt-in: set KARMADA_TPU_SOAK=1",
)


def _dep(name, replicas):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicas": replicas, "template": {"spec": {"containers": [
                {"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}}}}


def _policy(i, target):
    return PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name=f"p-{i}"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name=target)],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))))


@pytest.mark.parametrize("modes", [("Push",) * 6, ("Push", "Pull") * 3])
def test_threaded_chaos_soak(tmp_path, modes):
    cp = ControlPlane(backend="serial", persist_dir=str(tmp_path / "plane"))
    for i, mode in enumerate(modes):
        cp.add_member(f"m{i}", cpu_milli=48_000, sync_mode=mode)
    cp.runtime.serve()
    rng = random.Random(1)
    apps = []
    try:
        for i in range(10):
            n = f"app-{i}"
            cp.apply(_dep(n, rng.randint(2, 8)))
            cp.apply_policy(_policy(i, n))
            apps.append(n)

        end = time.time() + 90
        while time.time() < end:
            a = rng.randrange(4)
            if a == 0:
                m = cp.member(f"m{rng.randrange(len(modes))}")
                m.healthy = rng.random() < 0.8
            elif a == 1:
                cp.apply(_dep(rng.choice(apps), rng.randint(1, 10)))
            elif a == 2:
                cp.checkpoint()
            time.sleep(0.05)
        for i in range(len(modes)):
            cp.member(f"m{i}").healthy = True
        time.sleep(5)
    finally:
        cp.runtime.stop()
    cp.checkpoint()

    for n in apps:
        rb = cp.store.get(ResourceBinding.KIND, "default", f"{n}-deployment")
        want = cp.store.get("Deployment", "default", n).manifest["spec"]["replicas"]
        got = sum(tc.replicas for tc in rb.spec.clusters)
        assert got == want, (n, got, want)
        for tc in rb.spec.clusters:
            obj = cp.member(tc.name).get("Deployment", "default", n)
            assert obj is not None, (n, tc.name)
            assert obj.manifest["spec"]["replicas"] == tc.replicas
