"""Telemetry plane (obs/timeseries + obs/slo + obs/devprof): ring
bounds, snapshot/dump consistency, counter-delta reset semantics,
virtual-clock sampling inside a compressed soak, burn-rate math on
synthetic series, the regression watchdog, the disarmed
zero-compile/zero-cost contract, the /debug/timeseries + /debug/slo +
/debug/profile HTTP surface, and the `karmadactl top`/`profile` render
smoke."""

import json
import re
import urllib.request

import pytest

from karmada_tpu.obs import devprof
from karmada_tpu.obs import slo as obs_slo
from karmada_tpu.obs import timeseries as obs_ts
from karmada_tpu.utils.metrics import (
    Registry,
    exponential_buckets,
    quantile_from_buckets,
)

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process-wide sampler/evaluator disarmed."""
    yield
    obs_ts.disarm()
    obs_slo.disarm()


def make_registry():
    r = Registry()
    c = r.counter("karmada_test_events_total", "events", ("kind",))
    g = r.gauge("karmada_test_depth", "depth")
    h = r.histogram("karmada_test_latency_seconds", "latency",
                    buckets=exponential_buckets(0.001, 2, 10))
    return r, c, g, h


# -- Registry.snapshot() ------------------------------------------------------


def test_snapshot_structure_and_dump_consistency():
    """The structured snapshot and the text exposition must agree on
    every value — dump() stays the only text surface, snapshot() the
    only structured one, and they may never drift."""
    r, c, g, h = make_registry()
    c.inc(3, kind="a")
    c.inc(kind="b")
    g.set(7.5)
    for v in (0.002, 0.004, 0.1):
        h.observe(v)
    snap = r.snapshot()
    assert set(snap) == {"karmada_test_events_total", "karmada_test_depth",
                        "karmada_test_latency_seconds"}
    fam = snap["karmada_test_events_total"]
    assert fam["type"] == "counter" and fam["labels"] == ["kind"]
    values = {tuple(s["labels"]): s["value"] for s in fam["samples"]}
    assert values == {("a",): 3.0, ("b",): 1.0}
    hs = snap["karmada_test_latency_seconds"]["samples"][0]
    assert hs["count"] == 3 and hs["sum"] == pytest.approx(0.106)
    # cumulative buckets: monotone, last == count at +Inf only if all fit
    assert hs["buckets"] == sorted(hs["buckets"])
    # cross-check every dump line against the snapshot
    dump = r.dump()
    for line in dump.splitlines():
        if line.startswith("#") or not line:
            continue
        m = re.match(r"([a-z0-9_]+)(\{[^}]*\})? ([-+0-9.e]+|inf)$", line)
        assert m, line
        name, labels, val = m.group(1), m.group(2) or "", float(m.group(3))
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        if base not in snap:
            base = name  # unsuffixed family
        assert base in snap, line
        fam = snap[base]
        if fam["type"] != "histogram":
            lv = tuple(re.findall(r'="([^"]*)"', labels))
            got = {tuple(s["labels"]): s["value"] for s in fam["samples"]}
            assert got[lv] == val, line
        elif name.endswith("_count"):
            assert fam["samples"][0]["count"] == val
        elif name.endswith("_sum"):
            assert fam["samples"][0]["sum"] == pytest.approx(val)


def test_quantile_helper_shared_by_histogram():
    r, _c, _g, h = make_registry()
    for v in [0.001] * 90 + [0.3] * 10:
        h.observe(v)
    snap = r.snapshot()["karmada_test_latency_seconds"]
    s = snap["samples"][0]
    q = quantile_from_buckets(snap["bounds"], s["buckets"], s["count"], 0.5)
    assert q == h.quantile(0.5)
    assert q <= 0.002
    assert quantile_from_buckets(snap["bounds"], s["buckets"],
                                 s["count"], 0.99) >= 0.3
    assert quantile_from_buckets([], [], 0, 0.5) != \
        quantile_from_buckets([], [], 0, 0.5)  # NaN on empty


# -- ring bounds / eviction ---------------------------------------------------


def test_ring_bounds_and_eviction():
    r, c, _g, _h = make_registry()
    ring = obs_ts.MetricRing(capacity=4, registry=r)
    for i in range(10):
        c.inc(kind="a")
        assert ring.sample(float(i), force=True)
    assert len(ring) == 4
    assert ring.dropped == 6
    ts = [t for t, _ in ring.samples()]
    assert ts == [6.0, 7.0, 8.0, 9.0]  # oldest evicted first
    t0, t1, n = ring.window()
    assert (t0, t1, n) == (6.0, 9.0, 4)
    # n=0 really means zero samples, never the whole-ring [-0:] slice
    assert ring.samples(0) == []
    assert len(ring.samples(2)) == 2
    # a late out-of-order arrival (concurrent cycle + periodic threads
    # finishing snapshots in the wrong order) is dropped, keeping the
    # ring time-monotone — counter_delta must never read it as a reset
    assert not ring.sample(5.0, force=True)
    assert ring.out_of_order == 1
    assert [t for t, _ in ring.samples()] == [6.0, 7.0, 8.0, 9.0]
    # min_interval throttling on the SAMPLING clock; the per-sample
    # prepare hook (memory-gauge refresh) is only paid on ADMITTED
    # samples — a plane cycling every few ms must not poll devices
    # per cycle
    ring2 = obs_ts.MetricRing(capacity=8, registry=r, min_interval_s=1.0)
    calls = []
    assert ring2.sample(0.0, prepare=lambda: calls.append(1))
    assert not ring2.sample(0.5, prepare=lambda: calls.append(1))
    assert ring2.sample(1.5, prepare=lambda: calls.append(1))
    assert len(calls) == 2


def test_counter_delta_reset_aware():
    """A restarted process re-registers counters at 0: the windowed
    delta must count the post-reset value as increase and keep the
    pre-reset growth."""
    pts = [(0.0, 100.0), (1.0, 150.0), (2.0, 10.0), (3.0, 30.0)]
    assert obs_ts.counter_delta(pts) == pytest.approx(50 + 10 + 20)
    assert obs_ts.counter_delta([(0.0, 5.0)]) == 0.0
    assert obs_ts.counter_delta([]) == 0.0
    # end-to-end: series_window carries the reset-aware delta
    r, c, _g, _h = make_registry()
    ring = obs_ts.MetricRing(capacity=8, registry=r)
    c.inc(100, kind="a")
    ring.sample(0.0, force=True)
    c.inc(50, kind="a")
    ring.sample(1.0, force=True)
    series = obs_ts.series_window(ring.samples())
    key = 'karmada_test_events_total{kind="a"}'
    assert series[key]["delta"] == 50.0
    assert series[key]["points"] == [[0.0, 100.0], [1.0, 150.0]]


# -- burn-rate math on synthetic series --------------------------------------


def _counter_snap(value_bad: float, value_total: float) -> dict:
    return {
        "karmada_test_bad_total": {
            "type": "counter", "help": "", "labels": [],
            "samples": [{"labels": [], "value": value_bad}]},
        "karmada_test_all_total": {
            "type": "counter", "help": "", "labels": [],
            "samples": [{"labels": [], "value": value_total}]},
    }


class _FakeRing:
    def __init__(self, samples):
        self._s = samples

    def samples(self, n=None):
        return self._s if n is None else self._s[-n:]


def test_burn_rate_math_ratio_objective():
    obj = obs_slo.Objective(
        "errs", "ratio", target=0.99,
        bad=("karmada_test_bad_total", None),
        total=("karmada_test_all_total", None))
    ev = obs_slo.SloEvaluator(objectives=[obj], short_frac=0.25)
    # 8 samples; bad grows 5 over the long window (total 100), but all
    # of it in the FIRST half — the short window (last 2) is clean
    samples = []
    for i in range(8):
        bad = 5.0 if i >= 4 else i * (5.0 / 4)
        samples.append((float(i), _counter_snap(bad, i * (100.0 / 7))))
    payload = ev.evaluate(_FakeRing(samples))
    rec = payload["objectives"][0]
    # err long = 5/100 = 0.05; budget = 0.01 -> burn 5.0
    assert rec["burn_rate"]["long"] == pytest.approx(5.0, rel=1e-3)
    assert rec["burn_rate"]["short"] == pytest.approx(0.0)
    # multi-window rule: short is clean -> healthy despite long burn
    assert rec["healthy"] is True
    assert rec["budget_remaining"] == 0.0  # 0.05/0.01 clamps to 0
    # now both windows burn: bad grows steadily
    samples = [(float(i), _counter_snap(i * 2.0, i * 100.0))
               for i in range(8)]
    payload = ev.evaluate(_FakeRing(samples))
    rec = payload["objectives"][0]
    assert rec["burn_rate"]["long"] == pytest.approx(2.0, rel=1e-3)
    assert rec["burn_rate"]["short"] == pytest.approx(2.0, rel=1e-3)
    assert rec["healthy"] is False
    assert payload["healthy"] is False
    # gauges exported
    assert obs_slo.SLO_HEALTHY.value(slo="errs") == 0.0
    assert obs_slo.SLO_BURN_MILLI.value(slo="errs", window="long") == 2000.0


def test_burn_rate_latency_and_zero_objectives():
    r = Registry()
    h = r.histogram("karmada_test_lat_seconds", "x",
                    buckets=[0.1, 1.0, 10.0])
    viol = r.counter("karmada_test_viol_total", "x")
    lat = obs_slo.Objective("lat", "latency", target=0.9,
                            metric="karmada_test_lat_seconds",
                            threshold_s=1.0)
    zero = obs_slo.Objective("cons", "zero",
                             bad=("karmada_test_viol_total", None))
    ev = obs_slo.SloEvaluator(objectives=[lat, zero], short_frac=0.5)
    ring = obs_ts.MetricRing(capacity=16, registry=r)
    ring.sample(0.0, force=True)
    for v in [0.05] * 8 + [5.0] * 2:  # 20% of observations over 1s
        h.observe(v)
    ring.sample(1.0, force=True)
    payload = ev.evaluate(ring)
    lat_rec, zero_rec = payload["objectives"]
    # err 0.2 over budget 0.1 -> burn 2.0 in both windows -> unhealthy
    assert lat_rec["burn_rate"]["long"] == pytest.approx(2.0)
    assert lat_rec["healthy"] is False
    assert lat_rec["estimated_p"] == pytest.approx(10.0)  # bucket bound
    assert zero_rec["healthy"] is True and zero_rec["events"]["long"] == 0
    viol.inc()
    ring.sample(2.0, force=True)
    payload = ev.evaluate(ring)
    zero_rec = payload["objectives"][1]
    assert zero_rec["healthy"] is False
    assert zero_rec["events"]["long"] == 1.0
    # an off-bucket threshold rounds the error fraction UP: 1.0s
    # observations against a 0.7s deadline (between the 0.1 and 1.0
    # bounds) must count as misses, never as provably-good
    lat07 = obs_slo.Objective("lat07", "latency", target=0.9,
                              metric="karmada_test_lat_seconds",
                              threshold_s=0.7)
    ev07 = obs_slo.SloEvaluator(objectives=[lat07], short_frac=0.5)
    r07 = Registry()
    h07 = r07.histogram("karmada_test_lat_seconds", "x",
                        buckets=[0.1, 1.0, 10.0])
    ring07 = obs_ts.MetricRing(capacity=4, registry=r07)
    ring07.sample(0.0, force=True)
    for _ in range(10):
        h07.observe(1.0)  # every request missed the 0.7s deadline
    ring07.sample(1.0, force=True)
    rec07 = ev07.evaluate(ring07)["objectives"][0]
    assert rec07["error_fraction"]["long"] == 1.0
    assert rec07["healthy"] is False
    # no-data tri-state: a fresh ring with no observations judges None
    ev2 = obs_slo.SloEvaluator(objectives=[lat])
    r2 = Registry()
    r2.histogram("karmada_test_lat_seconds", "x", buckets=[0.1, 1.0])
    ring2 = obs_ts.MetricRing(capacity=4, registry=r2)
    ring2.sample(0.0, force=True)
    ring2.sample(1.0, force=True)
    rec = ev2.evaluate(ring2)["objectives"][0]
    assert rec["healthy"] is None
    assert rec["burn_rate"]["long"] is None


# -- regression watchdog ------------------------------------------------------


def _watchdog_samples(bps: float, span: float = 10.0, n: int = 6,
                      busy: bool = True):
    out = []
    for i in range(n):
        t = span * i / (n - 1)
        out.append((t, {
            "karmada_scheduler_schedule_attempts_total": {
                "type": "counter", "help": "",
                "labels": ["result", "schedule_type"],
                "samples": [{"labels": ["scheduled", "reconcile"],
                             "value": bps * t}]},
            "karmada_scheduler_queue_depth": {
                "type": "gauge", "help": "", "labels": ["queue"],
                "samples": [{"labels": ["active"],
                             "value": 5.0 if busy else 0.0}]},
        }))
    return out


def test_regression_watchdog_trip_and_clear():
    wd = obs_slo.RegressionWatchdog(baseline_bps=1000.0, floor_frac=0.5,
                                    min_window_bindings=100)
    # saturated window scheduling at 200 bps < floor 500 -> trip
    rec = wd.check(_watchdog_samples(200.0))
    assert rec["tripped"] is True
    assert rec["live_bps"] == pytest.approx(200.0, rel=0.01)
    assert obs_slo.REGRESSION_TRIPPED.value() == 1.0
    # recovered throughput clears it
    rec = wd.check(_watchdog_samples(800.0))
    assert rec["tripped"] is False
    assert obs_slo.REGRESSION_TRIPPED.value() == 0.0
    # light load (idle queue) never evaluates: verdict keeps last state
    rec = wd.check(_watchdog_samples(1.0, busy=False))
    assert rec["tripped"] is False and rec["live_bps"] is None
    assert rec["busy_frac"] == 0.0
    # too little traffic: same
    wd2 = obs_slo.RegressionWatchdog(baseline_bps=1000.0, floor_frac=0.5,
                                     min_window_bindings=10_000)
    rec = wd2.check(_watchdog_samples(200.0))
    assert rec["tripped"] is False and rec["live_bps"] is None


def test_baseline_envelope_loads_committed_bench():
    env = obs_slo.load_baseline_envelope()
    assert env is not None and env["bps"] > 0
    assert obs_slo.load_baseline_envelope("/nonexistent.json") is None


# -- virtual-clock sampling inside a compressed soak -------------------------


def test_virtual_clock_sampling_in_compressed_soak():
    """The scheduler's cycle hook stamps ring samples on the QUEUE
    clock — the soak's VirtualClock — so a compressed scenario yields a
    real virtual-time series with enough samples for burn-rate math
    (the bench --slo acceptance shape)."""
    import dataclasses

    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, ServiceModel, VirtualClock, get_scenario,
    )

    scenario = dataclasses.replace(get_scenario("steady"), n_bindings=80)
    clock = VirtualClock()
    model = ServiceModel()
    plane = ServeSlice(scenario, clock, model)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=3)
    ring = obs_ts.configure(capacity=2048, min_interval_s=0.0)
    obs_slo.configure(arm_watchdog=False)
    payload = driver.run()
    assert len(ring) >= 20
    t0, t1, _n = ring.window()
    # stamped on the virtual timeline, not wall time
    assert t0 >= 1_000_000.0 and t1 > t0
    slo_payload = payload["slo"]
    assert slo_payload["enabled"] and slo_payload["window"]["samples"] >= 20
    by_name = {o["name"]: o for o in slo_payload["objectives"]}
    assert by_name["schedule_p99"]["burn_rate"]["long"] is not None
    assert payload["scheduled"] > 0


# -- disarmed contract --------------------------------------------------------


def test_disarmed_zero_compile_and_zero_metric_cost():
    from karmada_tpu.ops import solver
    from karmada_tpu.utils.metrics import REGISTRY

    assert obs_ts.active() is None
    before_fams = set(REGISTRY.snapshot())
    c_before = solver._jit_cache_size()  # noqa: SLF001
    for i in range(1000):
        assert obs_ts.maybe_sample(float(i)) is False
    c_after = solver._jit_cache_size()  # noqa: SLF001
    assert c_before == c_after  # zero jit compiles (both None on old jax)
    assert set(REGISTRY.snapshot()) == before_fams  # zero new families
    # and the sampler's own counters did not move while disarmed
    assert obs_ts.SAMPLES_TOTAL.value() == obs_ts.SAMPLES_TOTAL.value()


# -- devprof ------------------------------------------------------------------


class _FakeDev:
    platform, id = "tpu", 0

    def memory_stats(self):
        return {"bytes_in_use": 1024, "peak_bytes_in_use": 2048,
                "bytes_limit": 4096}


def test_devprof_memory_gauges_and_cost_ledger():
    devprof.reset_for_tests()
    n = devprof.refresh_memory_gauges(devices=[_FakeDev()])
    assert n == 3
    assert devprof.DEVICE_MEMORY.value(device="tpu:0", kind="in_use") == 1024
    assert devprof.DEVICE_MEMORY.value(device="tpu:0", kind="peak") == 2048
    payload = devprof.state_payload()
    assert payload["last_memory"]["devices"][0]["in_use"] == 1024
    assert payload["last_memory"]["rss_bytes"] > 0  # the host floor
    devprof.record_cost("B8xC2:plain", {"flops": 10.0,
                                        "bytes_accessed": 20.0})
    devprof.record_cost("nope", None)  # absent analysis: not filed
    assert devprof.cost_ledger() == {"B8xC2:plain": {"flops": 10.0,
                                                     "bytes_accessed": 20.0}}
    stats = devprof.memory_stats_payload(devices=[_FakeDev()])
    assert stats[0]["memory_stats"]["bytes_limit"] == 4096


def test_aot_warm_harvests_cost_analysis():
    """ops/solver.aot_warm_compile returns the compiled executable's
    cost_analysis harvest (flops/bytes) — the aotcache ledger's cost
    column."""
    from karmada_tpu.estimator.general import GeneralEstimator
    from karmada_tpu.loadgen.driver import build_cluster
    from karmada_tpu.ops import solver, tensors
    from karmada_tpu.ops.aotcache import synth_items

    clusters = [build_cluster(f"m{i}") for i in range(2)]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(synth_items(8), cindex, GeneralEstimator())
    timings = solver.aot_warm_compile(batch, waves=4)
    assert timings["compile_s"] >= 0
    cost = timings["cost"]
    assert cost is not None and cost["flops"] > 0
    assert cost["bytes_accessed"] > 0


# -- HTTP + CLI smoke ---------------------------------------------------------


def fetch(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read().decode()


@pytest.fixture
def telemetry_plane(tmp_path):
    """A 2-cycle device-backend serve slice with the telemetry plane
    armed, served over the observability endpoint."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.utils.httpserve import ObservabilityServer

    obs_ts.configure(capacity=256, min_interval_s=0.0)
    obs_slo.configure(arm_watchdog=False)
    cp = ControlPlane(backend="device")
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement, PropagationPolicy, PropagationSpec, ResourceSelector,
    )

    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement())))
    for cycle in range(2):  # the "2-cycle serve"
        for i in range(3):
            cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
                      "metadata": {"name": f"app-{cycle}-{i}",
                                   "namespace": "default"},
                      "spec": {"replicas": 1, "template": {"spec": {
                          "containers": [{"name": "a", "resources": {
                              "requests": {"cpu": "100m"}}}]}}}})
        cp.tick()
    srv = ObservabilityServer(store=cp.store,
                              profile_dir=str(tmp_path / "profiles"))
    url = srv.start()
    try:
        yield cp, url
    finally:
        srv.stop()


def test_debug_timeseries_serves_15_series_over_2_cycles(telemetry_plane):
    _cp, url = telemetry_plane
    code, body = fetch(url + "/debug/timeseries")
    assert code == 200
    payload = json.loads(body)
    assert payload["enabled"] and payload["samples"] >= 2
    series = payload["series"]
    assert len(series) >= 15, f"only {len(series)} series"
    # counters carry window deltas, gauges carry last
    kinds = {rec["type"] for rec in series.values()}
    assert "counter" in kinds and "gauge" in kinds
    assert all(("delta" in rec) == (rec["type"] == "counter")
               for rec in series.values())
    # filters work
    code, body = fetch(url + "/debug/timeseries?n=2&prefix=karmada_scheduler")
    sub = json.loads(body)
    assert sub["returned_samples"] <= 2
    assert sub["series"] and all(k.startswith("karmada_scheduler")
                                 for k in sub["series"])
    # aggregate mode (?points=0, the karmadactl top poll): window
    # deltas/last values only — no per-series point lists serialized
    code, body = fetch(url + "/debug/timeseries?points=0")
    agg = json.loads(body)
    assert agg["series"] and all("points" not in rec
                                 for rec in agg["series"].values())
    assert len(body) < len(fetch(url + "/debug/timeseries")[1])


def test_debug_slo_and_top_render(telemetry_plane, capsys):
    _cp, url = telemetry_plane
    code, body = fetch(url + "/debug/slo")
    assert code == 200
    payload = json.loads(body)
    assert payload["enabled"]
    assert {o["name"] for o in payload["objectives"]} >= {
        "schedule_p99", "dwell_p99", "shed_ratio", "conservation",
        "estimator_errors"}
    # karmadactl top --endpoint renders the dashboard from the live plane
    from karmada_tpu.cli import main as cli_main

    rc = cli_main(["top", "--endpoint", url])
    assert rc == 0
    out = capsys.readouterr().out
    assert "telemetry window" in out
    assert "queue depth" in out and "cycle budget" in out
    assert "slo [" in out
    # disarmed plane: the dashboard says so instead of crashing
    obs_ts.disarm()
    rc = cli_main(["top", "--endpoint", url])
    assert rc == 0
    assert "disabled" in capsys.readouterr().out


def test_debug_profile_rejects_bad_input_as_json_400(telemetry_plane):
    """Input validation answers JSON, never a stack trace (no capture
    is started, so this stays cheap in-process)."""
    _cp, url = telemetry_plane
    import urllib.error

    try:
        code, body = fetch(url + "/debug/profile?seconds=abc")
    except urllib.error.HTTPError as e:
        code, body = e.code, e.read().decode()
    assert code == 400 and "error" in json.loads(body)


def test_debug_profile_writes_nonempty_artifact(tmp_path, capsys):
    """The acceptance shape: /debug/profile?seconds=1 on a live serve
    plane (CPU backend) yields a non-empty TensorBoard-loadable
    artifact.  Runs against a FRESH serve subprocess — in a long test
    session jax.profiler.start_trace scales with the process's
    executable population (tens of seconds), which measures the suite,
    not the endpoint."""
    import os
    import re as _re
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    plane = str(tmp_path / "plane")
    subprocess.run(
        [sys.executable, "-m", "karmada_tpu.cli", "--dir", plane, "init"],
        check=True, env=env, cwd=repo, capture_output=True)
    proc = subprocess.Popen(
        [sys.executable, "-m", "karmada_tpu.cli", "--dir", plane, "serve",
         "--backend", "serial", "--metrics-port", "0", "--telemetry"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    url = None
    try:
        for line in proc.stdout:  # serve prints the bound ephemeral port
            m = _re.search(r"observability endpoint at (http://\S+)", line)
            if m:
                url = m.group(1)
                break
        assert url, "serve never printed its observability endpoint"
        with urllib.request.urlopen(url + "/debug/profile?seconds=1",
                                    timeout=180) as r:
            rec = json.loads(r.read().decode())
        assert rec["ok"], rec
        assert rec["files"], "capture produced no artifacts"
        assert rec["total_bytes"] > 0
        assert any(f["bytes"] > 0 for f in rec["files"])
        # artifacts land under the plane dir (the profileflag contract)
        assert rec["dir"].startswith(os.path.join(plane, "profiles"))
        # karmadactl profile renders a second capture's inventory
        from karmada_tpu.cli import main as cli_main

        rc = cli_main(["profile", "--endpoint", url, "--seconds", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "captured" in out and "bytes" in out
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_serve_cycles_refresh_memory_attribution(telemetry_plane):
    """The per-guarded-cycle contract: after a 2-cycle serve with the
    plane armed, the memory attribution refreshed (RSS floor on CPU,
    per-device series where the backend reports stats)."""
    payload = devprof.state_payload()
    assert payload["last_memory"] is not None
    assert payload["last_memory"]["rss_bytes"] > 0
    assert devprof.PROCESS_MEMORY.value(kind="rss") > 0
