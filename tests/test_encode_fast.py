"""The C encode fast path must be tensor-identical to the Python loop.

karmada_tpu/native/encode_fast.c handles common-shape bindings and calls
the Python slow path (encode_one) on vocabulary misses and odd shapes;
behavior is DEFINED by the Python loop, so every SolverBatch field must
match bit-for-bit with the extension disabled.
"""

from __future__ import annotations

import dataclasses
import random

import numpy as np
import numpy.testing as npt
import pytest

import bench
from karmada_tpu import native
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.work import GracefulEvictionTask, TargetCluster
from karmada_tpu.ops import tensors

FIELDS = [
    "placement_id", "gvk_id", "class_id", "replicas", "uid_desc",
    "fresh", "non_workload", "nw_shortcut", "route", "b_valid",
    "prev_idx", "prev_val", "evict_idx", "pl_mask", "pl_strategy",
    "pl_static_w", "avail_milli", "req_milli", "req_pods", "api_ok",
]

pytestmark = pytest.mark.skipif(
    native.load_encode_fast() is None,
    reason=f"encode_fast unavailable: {native.encode_fast_error()}",
)


@pytest.fixture
def no_fast(monkeypatch):
    """Force the Python fallback for the control encoding."""
    monkeypatch.setattr(native, "_enc_mod", None)
    monkeypatch.setattr(native, "_enc_error", "disabled for parity test")


@pytest.mark.parametrize("seed", [3, 29])
def test_fast_path_tensor_parity(seed, no_fast, monkeypatch):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, 200)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 1024, placements)
    # corner shapes the fast path must hand back to Python: previous
    # assignments, eviction tasks, reschedule triggers, zero replicas
    extra = []
    for k in range(48):
        spec, st = items[k]
        extra.append((dataclasses.replace(
            spec,
            clusters=[TargetCluster(name=clusters[k % 200].name, replicas=2)],
            graceful_eviction_tasks=(
                [GracefulEvictionTask(from_cluster=clusters[0].name)]
                if k % 3 == 0 else []),
            reschedule_triggered_at=(50.0 if k % 2 else None),
            replicas=(0 if k % 5 == 0 else spec.replicas),
        ), st))
    # huge replica counts must take the host route from BOTH paths
    spec0, st0 = items[0]
    extra.append((dataclasses.replace(
        spec0, replicas=tensors.KERNEL_REPLICA_CAP + 1), st0))
    # list pairs (not tuples) must not crash the extension
    extra.append(list(items[1]))
    items = items + extra

    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)

    slow = tensors.encode_batch(items, cindex, est, cache=tensors.EncoderCache())
    # re-enable the real extension for the fast encoding
    monkeypatch.setattr(native, "_enc_mod", None)
    monkeypatch.setattr(native, "_enc_error", None)
    assert native.load_encode_fast() is not None
    fast = tensors.encode_batch(items, cindex, est, cache=tensors.EncoderCache())

    for f in FIELDS:
        npt.assert_array_equal(
            np.asarray(getattr(fast, f)), np.asarray(getattr(slow, f)),
            err_msg=f)


@pytest.mark.parametrize("empty_prop", [False, True])
def test_decode_fast_parity(no_fast, monkeypatch, empty_prop):
    """decode_fast must build identical target lists to the Python builder,
    including zero-replica lanes, non-workload rows, and error slots."""
    rng = random.Random(11)
    clusters = bench.build_fleet(rng, 64)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 256, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est,
                                 cache=tensors.EncoderCache())
    nb, C = batch.n_bindings, batch.C
    rows = []
    for b in range(nb):
        ks = sorted(rng.sample(range(batch.n_clusters), rng.randint(0, 5)))
        rows += [(b * C + c, rng.randint(0, 3)) for c in ks]
    idx = np.array([r[0] for r in rows] or [0], np.int32)
    val = np.array([r[1] for r in rows] or [0], np.int32)
    status = np.zeros(batch.B, np.int32)
    status[3] = tensors.STATUS_UNSCHEDULABLE  # error slot stays Python's

    kw = dict(enable_empty_workload_propagation=empty_prop, items=items)
    slow = tensors.decode_compact(batch, idx, val, status, **kw)
    monkeypatch.setattr(native, "_enc_mod", None)
    monkeypatch.setattr(native, "_enc_error", None)
    assert native.load_encode_fast() is not None
    fast = tensors.decode_compact(batch, idx, val, status, **kw)

    assert len(fast) == len(slow)
    for b, (f, s) in enumerate(zip(fast, slow)):
        if isinstance(s, Exception):
            assert type(f) is type(s), b
            continue
        assert [(t.name, t.replicas) for t in f] == \
               [(t.name, t.replicas) for t in s], b
