"""Dirty-set incremental solving (ops/dirty + scheduler/incremental).

The golden contract: every incremental cycle's merged placements are
BIT-EXACT against the full dense solve seeded from the same pre-cycle
capacity ledger — asserted here by forcing the built-in parity audit
every cycle (the audit IS the dense control) across random delta
streams at 0.01% / 0.1% / 5% churn, through vocabulary growth (roster
appends with new placements), cluster removal (structural rebuild ⇒
forced full solve + ledger reset), and a forced audit mismatch
(corrupted results ⇒ loud recovery by adopting the control's answer).

Also covered: the carried-ledger seeding really flows into pricing
(run_pipeline carry_state), write-back self-churn terminates, the
dirty kernel's clean/steady classification empties the dirty set on
quiet cycles, and the fused slot store composes with the shortlist
plane (PR-15 gap: --shortlist now arms under --resident-fused) with
parity on the 2-device mesh.
"""

import random

import numpy as np
import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.ops import dirty as dirty_mod
from karmada_tpu.ops import meshing, shortlist as sl, tensors
from karmada_tpu.resident import ResidentState
from karmada_tpu.resident.deltas import CycleDeltas
from karmada_tpu.scheduler import pipeline
from karmada_tpu.scheduler.incremental import (
    INC_AUDITS,
    INC_CYCLES,
    CycleReport,
    IncrementalSolver,
)

pytestmark = pytest.mark.incremental


@pytest.fixture(autouse=True)
def _no_mesh_leak():
    yield
    meshing.deactivate()


def _fleet(n, seed=0, pods=None):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, n)
    if pods is not None:
        for c in clusters:
            q = c.status.resource_summary.allocatable["pods"]
            c.status.resource_summary.allocatable["pods"] = (
                type(q).from_units(pods))
    return clusters


def _bindings(rng, n, placements, seed_tag=""):
    """ResourceBinding objects (the incremental roster is binding-
    addressed: keys + rvs + in-place write-back)."""
    out = []
    for i, (spec, status) in enumerate(
            bench.build_bindings(rng, n, placements)):
        out.append(ResourceBinding(
            metadata=ObjectMeta(namespace=spec.resource.namespace,
                                name=f"{seed_tag}{spec.resource.name}",
                                resource_version=1),
            spec=spec, status=status))
    return out


def _placements(rng, names, n=6, lo=4, hi=10):
    import tests.test_shortlist as ts

    return ts._affinity_placements(rng, names, n=n, lo=lo, hi=hi)


def _world(n_clusters=48, n_bindings=256, seed=11, pods=None, n_pl=6):
    rng = random.Random(seed)
    clusters = _fleet(n_clusters, seed=seed, pods=pods)
    names = [c.metadata.name for c in clusters]
    pls = _placements(rng, names, n=n_pl)
    bindings = _bindings(rng, n_bindings, pls)
    return rng, clusters, names, pls, bindings


def _churn(rng, clusters, bindings, n_rows, n_caps=0):
    """One watch window: bump n_rows bindings' replica targets and
    n_caps clusters' reported pod capacity.  Returns the CycleDeltas a
    DeltaTracker would have coalesced (cluster churn rides the resident
    plane's own rv sweep instead)."""
    touched = []
    for pos in rng.sample(range(len(bindings)), n_rows):
        rb = bindings[pos]
        rb.spec.replicas = max(1, rb.spec.replicas + rng.choice((-1, 1)))
        rb.metadata.resource_version += 1
        touched.append((rb.namespace, rb.name))
    for c in rng.sample(clusters, n_caps):
        q = c.status.resource_summary.allocatable["pods"]
        c.status.resource_summary.allocatable["pods"] = (
            type(q).from_units(max(8, int(q.value()) + rng.choice(
                (-4, 4)))))
        c.metadata.resource_version += 1
    return CycleDeltas(bindings_touched=touched)


def _static_world(seed=23, n_clusters=32, n_bindings=128):
    """Duplicated/StaticWeight-only placements over an ample fleet: no
    dynamic-divergence, so quiet cycles classify every row clean."""
    from karmada_tpu.models.policy import (
        ClusterAffinity, Placement, ReplicaSchedulingStrategy,
        REPLICA_SCHEDULING_DIVIDED, REPLICA_DIVISION_WEIGHTED)

    rng = random.Random(seed)
    clusters = _fleet(n_clusters, seed=seed)
    names = [c.metadata.name for c in clusters]
    pls = []
    for j in range(6):
        picked = rng.sample(names, rng.randint(4, 10))
        rs = (ReplicaSchedulingStrategy(
                  replica_scheduling_type="Duplicated") if j % 2 else
              ReplicaSchedulingStrategy(
                  replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                  replica_division_preference=REPLICA_DIVISION_WEIGHTED))
        pls.append(Placement(
            cluster_affinity=ClusterAffinity(cluster_names=picked),
            replica_scheduling=rs))
    return rng, clusters, _bindings(rng, n_bindings, pls)


def _settle(solver, clusters, bindings):
    """adopt + write-back + drain the self-churn (written-back rows
    re-solve once, reproduce, and go quiet)."""
    rep = solver.adopt(clusters, bindings)
    assert rep.mode == "full" and rep.reason == "adopt"
    assert solver.write_back() > 0
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.mode == "incremental" and rep.audit_outcome == "ok"
    assert solver.write_back() == 0  # identical answers: no rv bumps
    return rep


# -- the churn property ------------------------------------------------------


@pytest.mark.parametrize("churn_frac", [0.0001, 0.001, 0.05])
def test_churn_stream_bit_exact_every_cycle(churn_frac):
    rng, clusters, names, pls, bindings = _world(
        n_clusters=48, n_bindings=256, seed=17, pods=64)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)

    n_rows = max(1, int(len(bindings) * churn_frac))
    for cyc in range(5):
        deltas = _churn(rng, clusters, bindings, n_rows,
                        n_caps=(1 if cyc % 2 else 0))
        rep = solver.cycle(clusters, bindings, deltas, force_audit=True)
        assert rep.mode == "incremental"
        assert rep.audit_outcome == "ok", (churn_frac, cyc, rep)
        assert rep.dirty >= n_rows
        # dirty-ONLY: the compact sub-batch never balloons to the roster
        if churn_frac < 0.01:
            assert rep.dirty < len(bindings) // 2, rep
        assert sum(rep.groups) == rep.dirty
        solver.write_back()


def test_quiet_cycle_empty_dirty_set():
    """Steady state: no churn => the kernel classifies every row clean
    and the cycle dispatches zero groups.  Static-only fixture: dynamic
    rows that stay divergent (assigned != replicas) are ALWAYS sensitive
    by design — that case is covered by the fixed-point test below."""
    rng, clusters, bindings = _static_world(seed=23)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    d0 = dirty_mod.DIRTY_ROWS.value()
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.dirty == 0 and rep.groups == []
    assert rep.audit_outcome == "ok"
    assert dirty_mod.DIRTY_ROWS.value() == d0
    assert dirty_mod.DIRTY_FRACTION.value() == 0.0


def test_quiet_cycles_reach_fixed_point():
    """Mixed fixture (includes dynamic-weight rows): the persistent
    dirty set — divergent/unplaceable rows that must retry each cycle —
    stabilizes at a small fixed point across quiet cycles."""
    _rng, clusters, _names, _pls, bindings = _world(
        n_clusters=32, n_bindings=128, seed=23)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    reps = [solver.cycle(clusters, bindings, CycleDeltas(),
                         force_audit=True) for _ in range(3)]
    assert all(r.audit_outcome == "ok" for r in reps)
    counts = {r.dirty for r in reps}
    assert len(counts) == 1, reps  # fixed point
    assert reps[0].dirty < len(bindings) // 4, reps[0]


def test_vocabulary_growth_roster_append():
    """Appended bindings with a NEW placement (placement-vocabulary
    growth) force-dirty only themselves; parity holds."""
    rng, clusters, names, pls, bindings = _world(
        n_clusters=48, n_bindings=192, seed=29)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    grown = bindings + _bindings(
        rng, 24, _placements(rng, names, n=2, lo=3, hi=8),
        seed_tag="grown-")
    rep = solver.cycle(clusters, grown, CycleDeltas(), force_audit=True)
    assert rep.mode == "incremental"  # append is NOT a full solve
    assert rep.audit_outcome == "ok"
    assert rep.dirty >= 24
    assert rep.dirty < len(grown) // 2
    solver.write_back()
    rep = solver.cycle(clusters, grown, CycleDeltas(), force_audit=True)
    assert rep.audit_outcome == "ok"


def test_cluster_removal_forces_full_solve_and_recovers():
    rng, clusters, names, pls, bindings = _world(
        n_clusters=48, n_bindings=192, seed=31)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    solver.write_back()
    f0 = INC_CYCLES.value(mode="full")
    shrunk = clusters[:-4]  # membership change: structural rebuild
    rep = solver.cycle(shrunk, bindings, CycleDeltas())
    assert rep.mode == "full" and rep.reason == "plane-rebuild"
    assert INC_CYCLES.value(mode="full") == f0 + 1
    solver.write_back()
    # ...and the plane settles back into incremental operation
    rep = solver.cycle(shrunk, bindings, CycleDeltas(), force_audit=True)
    assert rep.mode == "incremental" and rep.audit_outcome == "ok"
    # the persistent dirty set (rows the shrink left divergent) reaches
    # its fixed point; parity keeps holding
    r1 = solver.cycle(shrunk, bindings, CycleDeltas(), force_audit=True)
    r2 = solver.cycle(shrunk, bindings, CycleDeltas(), force_audit=True)
    assert r1.audit_outcome == "ok" and r2.audit_outcome == "ok"
    assert r1.dirty == r2.dirty < len(bindings)


def test_forced_audit_mismatch_recovery():
    """Corrupted incremental results are caught by the audit, recovered
    from the control, and announced on the lifecycle ledger."""
    from karmada_tpu.obs import events as ev

    _rng, clusters, _names, _pls, bindings = _world(
        n_clusters=32, n_bindings=128, seed=37)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    pos = next(p for p, r in solver.results.items()
               if not isinstance(r, Exception))
    good = solver.results[pos]
    solver.results[pos] = []  # diverged state (placements dropped)
    m0 = INC_AUDITS.value(outcome="mismatch")
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.audit_outcome == "mismatch"
    assert INC_AUDITS.value(outcome="mismatch") == m0 + 1
    # recovery adopted the control's answer
    assert ({t.name: t.replicas for t in solver.results[pos]}
            == {t.name: t.replicas for t in good})
    recent = ev.state_payload(n=16)["recent"]
    assert any(e.get("reason") == ev.REASON_INCREMENTAL_AUDIT_MISMATCH
               for e in recent), recent
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.audit_outcome == "ok"


# -- ledger mechanics ---------------------------------------------------------


def test_carry_state_seed_changes_pricing():
    """run_pipeline(carry_state=...) must actually flow into the solve:
    seeding a previous run's consumption (scaled up) moves placements on
    a tight fleet, and the seed object itself is never mutated."""
    rng = random.Random(41)
    clusters = _fleet(24, seed=41, pods=24)
    cindex = tensors.ClusterIndex.build(clusters)
    names = [c.metadata.name for c in clusters]
    items = bench.build_bindings(rng, 96, _placements(
        rng, names, n=4, lo=4, hi=8))
    est = GeneralEstimator()
    base = pipeline.run_pipeline(items, cindex, est, chunk=32, waves=1,
                                 carry=True, collect_carry=True)
    assert base.carry is not None and not base.carry.empty()
    seed = base.carry.copy()
    for arr in seed.milli.values():
        arr *= 40
    if seed.pods is not None:
        seed.pods *= 40
    before = {k: v.copy() for k, v in seed.milli.items()}
    seeded = pipeline.run_pipeline(items, cindex, est, chunk=32, waves=1,
                                   carry=True, carry_state=seed,
                                   collect_carry=True)
    for k, v in before.items():
        assert np.array_equal(seed.milli[k], v), "seed object mutated"
    moved = sum(
        1 for i, want in base.results.items()
        if not isinstance(want, Exception)
        and ({t.name: t.replicas for t in want}
             != ({t.name: t.replicas for t in seeded.results[i]}
                 if not isinstance(seeded.results[i], Exception)
                 else None)))
    assert moved > 0, "a 40x consumption seed moved no placement"


def test_capacity_churn_retires_ledger_lanes():
    """A cluster status write retires the carried consumption on its
    lane (reported availability now embeds it) — and parity still holds
    through the retire.  Static ample-capacity fixture: with a quiet
    dirty set the cycle adds no consumption of its own, so the full
    retire leaves the ledger exactly empty."""
    rng, clusters, bindings = _static_world(seed=43)
    state = ResidentState(audit_interval=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0)
    _settle(solver, clusters, bindings)
    assert not solver.ledger.empty()
    # every cluster reports fresh capacity: the whole ledger retires
    for c in clusters:
        q = c.status.resource_summary.allocatable["pods"]
        c.status.resource_summary.allocatable["pods"] = (
            type(q).from_units(int(q.value())))
        c.metadata.resource_version += 1
    rep = solver.cycle(clusters, bindings, CycleDeltas(),
                       force_audit=True)
    assert rep.audit_outcome == "ok"
    for arr in solver.ledger.milli.values():
        assert not arr.any()


# -- fused slot store x shortlist (PR-15 arming gap) -------------------------


def _fused_shortlist_world(seed=47):
    rng, clusters, names, pls, bindings = _world(
        n_clusters=64, n_bindings=192, seed=seed)
    state = ResidentState(audit_interval=0, fused=True)
    cfg = sl.ShortlistConfig(k=16, min_cells=0, union_frac=1.0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=64,
                               audit_every=0, shortlist=cfg)
    return rng, clusters, bindings, state, solver


def test_fused_shortlist_armed_and_bit_exact():
    rng, clusters, bindings, state, solver = _fused_shortlist_world()
    disp0 = sl.SHORTLIST_DISPATCHES.value()
    fb0 = sl.SHORTLIST_FALLBACKS.total()
    rep = solver.adopt(clusters, bindings)
    assert rep.mode == "full"
    # the fused gather really ran, and the shortlist really dispatched
    assert state.fused_cycles > 0
    assert sl.SHORTLIST_DISPATCHES.value() > disp0
    assert sl.SHORTLIST_FALLBACKS.total() == fb0, "silent fallback"
    # independent control: fresh host encode, dense solve, same seed
    items = [(rb.spec, rb.status) for rb in bindings]
    dense = pipeline.run_pipeline(
        items, tensors.ClusterIndex.build(clusters), GeneralEstimator(),
        chunk=64, waves=1, carry=True)
    assert dense.results.keys() == solver.results.keys()
    for i, want in dense.results.items():
        got = solver.results[i]
        if isinstance(want, Exception):
            assert isinstance(got, type(want)), (i, want, got)
        else:
            assert ({t.name: t.replicas for t in got}
                    == {t.name: t.replicas for t in want}), i
    # steady churned cycles stay fused + shortlisted + bit-exact
    solver.write_back()
    solver.cycle(clusters, bindings, CycleDeltas())
    for _ in range(3):
        deltas = _churn(rng, clusters, bindings, 4, n_caps=1)
        rep = solver.cycle(clusters, bindings, deltas, force_audit=True)
        assert rep.audit_outcome == "ok"
        solver.write_back()
    assert sl.SHORTLIST_FALLBACKS.total() == fb0, "silent fallback"


def test_fused_shortlist_mesh_2dev_parity():
    import jax

    rng, clusters, bindings, state, solver = _fused_shortlist_world(
        seed=53)
    items = [(rb.spec, rb.status) for rb in bindings]
    dense = pipeline.run_pipeline(
        items, tensors.ClusterIndex.build(clusters), GeneralEstimator(),
        chunk=64, waves=1, carry=True)
    plan = meshing.activate((1, 2), devices=jax.devices()[:2])
    assert plan is not None
    try:
        _settle(solver, clusters, bindings)
        rep = solver.cycle(clusters, bindings, CycleDeltas(),
                           force_audit=True)
        assert rep.mode == "incremental" and rep.audit_outcome == "ok"
    finally:
        meshing.deactivate()
    assert dense.results.keys() == solver.results.keys()
    for i, want in dense.results.items():
        got = solver.results[i]
        if isinstance(want, Exception):
            assert isinstance(got, type(want)), (i, want, got)
        else:
            assert ({t.name: t.replicas for t in got}
                    == {t.name: t.replicas for t in want}), i


# -- report / plumbing --------------------------------------------------------


def test_report_shape_and_waves_guard():
    state = ResidentState(audit_interval=0)
    with pytest.raises(AssertionError):
        IncrementalSolver(state, GeneralEstimator(), waves=2)
    rep = CycleReport()
    assert rep.mode == "incremental" and rep.groups == []
