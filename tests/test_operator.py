"""karmada-operator: install/probe/deinstall control planes from Karmada CRs.

Reference: operator/pkg/ — Karmada CR (operator/pkg/apis/operator/v1alpha1/
type.go:33), workflow engine (workflow/job.go), install tasks (tasks/init:
cert -> etcd -> apiserver -> components -> wait).
"""

from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.operator import (
    COND_READY,
    INSTALL_PHASES,
    Karmada,
    KarmadaComponents,
    KarmadaOperator,
    KarmadaSpec,
)
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


def mgmt(tmp_path):
    store = ObjectStore()
    runtime = Runtime()
    op = KarmadaOperator(store, runtime, base_dir=str(tmp_path))
    return store, runtime, op


def test_install_runs_workflow_and_reaches_running(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    assert cr.status.api_ready
    conds = {c.type: c.status for c in cr.status.conditions}
    for phase in INSTALL_PHASES:
        assert conds[phase] == "True", phase
    assert conds[COND_READY] == "True"
    # the installed plane is a live control plane
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.tick()
    assert plane.store.try_get("Cluster", "", "m1") is not None


def test_installed_plane_honors_spec(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(
        metadata=ObjectMeta(name="tuned"),
        spec=KarmadaSpec(
            components=KarmadaComponents(descheduler=True),
            feature_gates={"FederatedQuotaEnforcement": True},
        ),
    ))
    runtime.tick()
    plane = op.plane("tuned")
    assert plane.descheduler is not None
    assert plane.gates.enabled("FederatedQuotaEnforcement")


def test_deinstall_on_delete(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="temp")))
    runtime.tick()
    assert op.plane("temp") is not None
    store.delete(Karmada.KIND, "", "temp")
    runtime.tick()
    assert op.plane("temp") is None


def test_reinstall_resumes_persisted_state(tmp_path):
    """Deinstall + reinstall from the same CR resumes the plane's data
    (the operator's etcd-PV-survives semantics)."""
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.tick()
    plane.checkpoint()
    store.delete(Karmada.KIND, "", "prod")
    runtime.tick()
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane2 = op.plane("prod")
    assert plane2.store.try_get("Cluster", "", "m1") is not None


def test_spec_change_upgrades_live_plane(tmp_path):
    """A spec change on a live plane triggers the upgrade workflow: the
    plane rebuilds under the new spec from the same persisted state
    (reference operator upgrade/reconfigure)."""
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.checkpoint()
    old_plane = plane

    def flip(cr: Karmada) -> None:
        cr.spec.components = KarmadaComponents(
            scheduler_backend="serial", descheduler=True)
        cr.spec.feature_gates = {"MultiClusterService": True}
    store.mutate(Karmada.KIND, "", "prod", flip)
    runtime.tick()

    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    new_plane = op.plane("prod")
    assert new_plane is not old_plane
    # state survived through the persisted dir
    assert new_plane.store.try_get("Cluster", "", "m1") is not None
    assert new_plane.gates.enabled("MultiClusterService") is True
    # observed generation is now current: a further probe does not rebuild
    runtime.tick()
    assert op.plane("prod") is new_plane
