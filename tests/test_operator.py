"""karmada-operator: install/probe/deinstall control planes from Karmada CRs.

Reference: operator/pkg/ — Karmada CR (operator/pkg/apis/operator/v1alpha1/
type.go:33), workflow engine (workflow/job.go), install tasks (tasks/init:
cert -> etcd -> apiserver -> components -> wait).
"""

from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.operator import (
    COND_READY,
    INSTALL_PHASES,
    Karmada,
    KarmadaComponents,
    KarmadaOperator,
    KarmadaSpec,
)
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


def mgmt(tmp_path):
    store = ObjectStore()
    runtime = Runtime()
    op = KarmadaOperator(store, runtime, base_dir=str(tmp_path))
    return store, runtime, op


def test_install_runs_workflow_and_reaches_running(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    assert cr.status.api_ready
    conds = {c.type: c.status for c in cr.status.conditions}
    for phase in INSTALL_PHASES:
        assert conds[phase] == "True", phase
    assert conds[COND_READY] == "True"
    # the installed plane is a live control plane
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.tick()
    assert plane.store.try_get("Cluster", "", "m1") is not None


def test_installed_plane_honors_spec(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(
        metadata=ObjectMeta(name="tuned"),
        spec=KarmadaSpec(
            components=KarmadaComponents(descheduler=True),
            feature_gates={"FederatedQuotaEnforcement": True},
        ),
    ))
    runtime.tick()
    plane = op.plane("tuned")
    assert plane.descheduler is not None
    assert plane.gates.enabled("FederatedQuotaEnforcement")


def test_deinstall_on_delete(tmp_path):
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="temp")))
    runtime.tick()
    assert op.plane("temp") is not None
    store.delete(Karmada.KIND, "", "temp")
    runtime.tick()
    assert op.plane("temp") is None


def test_reinstall_resumes_persisted_state(tmp_path):
    """Deinstall + reinstall from the same CR resumes the plane's data
    (the operator's etcd-PV-survives semantics)."""
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.tick()
    plane.checkpoint()
    store.delete(Karmada.KIND, "", "prod")
    runtime.tick()
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane2 = op.plane("prod")
    assert plane2.store.try_get("Cluster", "", "m1") is not None


def test_spec_change_upgrades_live_plane(tmp_path):
    """A spec change on a live plane triggers the upgrade workflow: the
    plane rebuilds under the new spec from the same persisted state
    (reference operator upgrade/reconfigure)."""
    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    plane = op.plane("prod")
    plane.add_member("m1")
    plane.checkpoint()
    old_plane = plane

    def flip(cr: Karmada) -> None:
        cr.spec.components = KarmadaComponents(
            scheduler_backend="serial", descheduler=True)
        cr.spec.feature_gates = {"MultiClusterService": True}
    store.mutate(Karmada.KIND, "", "prod", flip)
    runtime.tick()

    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    new_plane = op.plane("prod")
    assert new_plane is not old_plane
    # state survived through the persisted dir
    assert new_plane.store.try_get("Cluster", "", "m1") is not None
    assert new_plane.gates.enabled("MultiClusterService") is True
    # observed generation is now current: a further probe does not rebuild
    runtime.tick()
    assert op.plane("prod") is new_plane


def test_cert_material_issued_and_ca_stable(tmp_path):
    """tasks/init/cert.go analog: CA + per-component leaf credentials on
    disk; the CA survives reinstall so member credentials stay valid."""
    import json
    import os

    from karmada_tpu.operator import CERT_COMPONENTS

    store, runtime, op = mgmt(tmp_path)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    pki = os.path.join(str(tmp_path), "prod", "pki")
    with open(os.path.join(pki, "ca.json")) as f:
        ca1 = json.load(f)
    for comp in CERT_COMPONENTS:
        with open(os.path.join(pki, f"{comp}.json")) as f:
            leaf = json.load(f)
        assert leaf["fingerprint"] and leaf["expires_at"] > leaf["issued_at"]
    # component config rendered into the plane carries the scheduler cert
    plane = op.plane("prod")
    cm = plane.store.get("ConfigMap", "karmada-system", "scheduler")
    assert cm.manifest["data"]["cert"]

    # reinstall (delete CR, recreate): CA material is reused
    store.delete(Karmada.KIND, "", "prod")
    runtime.tick()
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    with open(os.path.join(pki, "ca.json")) as f:
        ca2 = json.load(f)
    assert ca1["secret"] == ca2["secret"]


def test_install_fails_midway_then_recovers(tmp_path):
    """A task failure mid-graph (apiserver phase) marks the earlier phases
    True, the failed one False, phase Failed — and the next reconcile
    completes the install once the fault clears (workflow retry
    semantics, operator/pkg/workflow/job.go)."""
    from karmada_tpu.operator import (
        PHASE_APISERVER,
        PHASE_CERT,
        PHASE_STORE,
    )

    store = ObjectStore()
    runtime = Runtime()
    faults = {"armed": True}

    def inject(phase, name):
        if phase == PHASE_APISERVER and faults["armed"]:
            raise RuntimeError("injected: apiserver bringup failed")

    op = KarmadaOperator(store, runtime, base_dir=str(tmp_path),
                         fault_injector=inject)
    store.create(Karmada(metadata=ObjectMeta(name="prod")))
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    conds = {c.type: c.status for c in cr.status.conditions}
    assert cr.status.phase == "Failed"
    assert conds[PHASE_CERT] == "True"
    assert conds[PHASE_STORE] == "True"
    assert conds[PHASE_APISERVER] == "False"
    assert not cr.status.api_ready
    assert op.plane("prod") is None

    # the fault clears; the operator's retry completes the graph
    faults["armed"] = False
    op.worker.enqueue("prod")
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    assert cr.status.api_ready
    assert op.plane("prod") is not None


def test_failed_upgrade_rolls_back_to_previous_spec(tmp_path):
    """Upgrade rollback: a spec change whose install fails restores the
    previously-installed spec from the same data dir; the plane keeps
    serving and the CR records UpgradeFailed/RolledBack."""
    from karmada_tpu.operator import PHASE_COMPONENTS

    store = ObjectStore()
    runtime = Runtime()
    # one-shot fault: the BAD spec's component rollout fails; the rollback
    # install (old, known-good spec) succeeds
    faults = {"remaining": 0}

    def inject(phase, name):
        if phase == PHASE_COMPONENTS and faults["remaining"] > 0:
            faults["remaining"] -= 1
            raise RuntimeError("injected: component rollout failed")

    op = KarmadaOperator(store, runtime, base_dir=str(tmp_path),
                         fault_injector=inject)
    store.create(Karmada(metadata=ObjectMeta(name="prod"), spec=KarmadaSpec(
        components=KarmadaComponents(descheduler=False))))
    runtime.tick()
    assert store.get(Karmada.KIND, "", "prod").status.phase == "Running"
    plane_before = op.plane("prod")
    plane_before.add_member("m1")
    plane_before.tick()

    # the upgrade's component rollout will fail (once)
    faults["remaining"] = 1

    def change(obj):
        obj.spec.components.descheduler = True
    store.mutate(Karmada.KIND, "", "prod", change)
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    conds = {c.type: (c.status, c.reason) for c in cr.status.conditions}
    assert conds["UpgradeFailed"] == ("True", "RolledBack")
    assert cr.status.phase == "Running"  # rolled back and serving
    assert cr.status.api_ready
    plane = op.plane("prod")
    assert plane is not None
    # the rolled-back plane runs the OLD spec (descheduler off) even
    # though the CR still carries the bad new spec — and kept the data
    assert plane.descheduler is None
    assert plane.store.try_get("Cluster", "", "m1") is not None

    # a FIXED spec (new generation) upgrades cleanly afterwards
    def change2(obj):
        obj.spec.components.search = False  # a real spec change
    store.mutate(Karmada.KIND, "", "prod", change2)
    runtime.tick()
    cr = store.get(Karmada.KIND, "", "prod")
    assert cr.status.phase == "Running"
    plane2 = op.plane("prod")
    assert plane2 is not None and plane2 is not plane_before
    # the clean upgrade records the new spec as the rollback target and
    # clears the stale UpgradeFailed signal
    assert op.installed_spec["prod"].components.search is False
    up = next(c for c in cr.status.conditions if c.type == "UpgradeFailed")
    assert up.status == "False" and up.reason == "Recovered"
