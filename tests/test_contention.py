"""Within-batch capacity contention: waves=B must equal serial one-at-a-time.

SURVEY §7 "Hard parts" requires a defined capacity-contention policy for the
batched solver.  The policy: schedule_batch(waves=G) splits the chunk into G
sequential waves; wave k prices against the snapshot minus everything waves
<k consumed.  waves == B is bit-equal to the reference's serial semantics
(one binding at a time against a decremented snapshot,
pkg/scheduler/core/generic_scheduler.go:71); production uses a small G and
documents that bindings WITHIN a wave share a snapshot.
"""

import copy
import os
import random

import pytest

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.solver import solve
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


def mk_cluster(name, cpu_milli, mem_units, pods):
    return Cluster(
        metadata=ObjectMeta(name=name),
        spec=ClusterSpec(),
        status=ClusterStatus(
            api_enablements=[APIEnablement(GVK[0], [GVK[1]])],
            resource_summary=ResourceSummary(
                allocatable={
                    "cpu": Quantity.from_milli(cpu_milli),
                    "memory": Quantity.from_units(mem_units),
                    "pods": Quantity.from_units(pods),
                },
            ),
        ),
    )


def mk_binding(b, replicas, cpu_milli, mem_units, dynamic=True):
    pref = (
        ClusterPreferences(dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)
        if dynamic
        else None
    )
    rs = ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
        replica_division_preference=(
            REPLICA_DIVISION_WEIGHTED if dynamic else REPLICA_DIVISION_AGGREGATED
        ),
        weight_preference=pref,
    )
    spec = ResourceBindingSpec(
        resource=ObjectReference(
            api_version=GVK[0], kind=GVK[1], namespace="default",
            name=f"app-{b}", uid=f"uid-{b}",
        ),
        replicas=replicas,
        replica_requirements=ReplicaRequirements(resource_request={
            "cpu": Quantity.from_milli(cpu_milli),
            "memory": Quantity.from_units(mem_units),
        }),
        placement=Placement(replica_scheduling=rs),
    )
    return spec, ResourceBindingStatus()


def consume(cluster: Cluster, replicas: int, cpu_milli: int, mem_units: int):
    """Decrement the snapshot the way the wave accumulator does: replicas x
    request added to `allocated` (cpu milli, memory units, 1 pod/replica)."""
    s = cluster.status.resource_summary
    alloc = s.allocated
    alloc["cpu"] = Quantity.from_milli(
        alloc.get("cpu", Quantity(0)).milli + replicas * cpu_milli
    )
    alloc["memory"] = Quantity.from_units(
        alloc.get("memory", Quantity(0)).value() + replicas * mem_units
    )
    alloc["pods"] = Quantity.from_units(
        alloc.get("pods", Quantity(0)).value() + replicas
    )


def serial_one_at_a_time(items, clusters):
    """The reference semantics: each binding sees the previous ones' usage.

    Consumption is the positive DELTA over the binding's previous
    assignment — kept replicas are already in the snapshot's allocated
    totals (same rule as the wave accumulator in ops/solver.py).
    """
    clusters = copy.deepcopy(clusters)
    estimator = GeneralEstimator()
    cal = serial.make_cal_available([estimator])
    results = []
    for spec, st in items:
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001
            results.append(e)
            continue
        results.append(want)
        by_name = {c.name: c for c in clusters}
        prev = {tc.name: tc.replicas for tc in spec.clusters}
        req = spec.replica_requirements.resource_request
        for tc in want:
            delta = max(tc.replicas - prev.get(tc.name, 0), 0)
            consume(
                by_name[tc.name], delta,
                req["cpu"].milli, req["memory"].value(),
            )
    return results


def run_case(items, clusters):
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator, pad_bindings=False)
    rep, sel, status = solve(batch, waves=batch.B)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    want = serial_one_at_a_time(items, clusters)
    for b, (w, g) in enumerate(zip(want, got)):
        if isinstance(w, Exception):
            assert isinstance(g, type(w)), (b, w, g)
            continue
        wm = {tc.name: tc.replicas for tc in w}
        gm = {tc.name: tc.replicas for tc in g}
        assert gm == wm, (b, wm, gm)


def test_contention_single_small_cluster():
    """N dynamic bindings fighting over one small cluster: later bindings
    must see the decremented capacity and go unschedulable when it runs out,
    exactly as the serial one-at-a-time path does."""
    clusters = [mk_cluster("m1", cpu_milli=10_000, mem_units=100, pods=100)]
    # each replica: 1000m cpu -> cluster fits 10 replicas total
    items = [mk_binding(b, replicas=4, cpu_milli=1000, mem_units=1) for b in range(4)]
    run_case(items, clusters)
    # sanity on the serial meaning itself: 4+4 fit, the rest don't
    want = serial_one_at_a_time(items, copy.deepcopy(clusters))
    fits = [w for w in want if not isinstance(w, Exception)]
    fails = [w for w in want if isinstance(w, Exception)]
    assert len(fits) == 2 and len(fails) == 2
    assert all(isinstance(w, serial.UnschedulableError) for w in fails)


def test_contention_two_clusters_spillover():
    """When the preferred cluster drains, later bindings spill to the other."""
    clusters = [
        mk_cluster("big", cpu_milli=8000, mem_units=64, pods=50),
        mk_cluster("small", cpu_milli=4000, mem_units=64, pods=50),
    ]
    items = [mk_binding(b, replicas=3, cpu_milli=1000, mem_units=1) for b in range(4)]
    run_case(items, clusters)


def test_contention_aggregated_strategy():
    clusters = [
        mk_cluster("a", cpu_milli=6000, mem_units=64, pods=50),
        mk_cluster("b", cpu_milli=6000, mem_units=64, pods=50),
        mk_cluster("c", cpu_milli=3000, mem_units=64, pods=50),
    ]
    items = [
        mk_binding(b, replicas=3, cpu_milli=1000, mem_units=1, dynamic=False)
        for b in range(5)
    ]
    run_case(items, clusters)


def test_contention_pods_axis():
    """Pod-count capacity (no resource shortage) must decrement too."""
    clusters = [mk_cluster("m1", cpu_milli=10**9, mem_units=10**6, pods=10)]
    items = [mk_binding(b, replicas=3, cpu_milli=10, mem_units=0) for b in range(5)]
    run_case(items, clusters)


def test_contention_random_fuzz():
    rng = random.Random(42)
    for _ in range(6):
        clusters = [
            mk_cluster(
                f"m{i}",
                cpu_milli=rng.randint(2000, 20000),
                mem_units=rng.randint(8, 128),
                pods=rng.randint(5, 60),
            )
            for i in range(rng.randint(2, 6))
        ]
        items = [
            mk_binding(
                b,
                replicas=rng.randint(1, 8),
                cpu_milli=rng.choice([100, 250, 500, 1000]),
                mem_units=rng.choice([1, 2]),
                dynamic=rng.random() < 0.7,
            )
            for b in range(rng.randint(3, 10))
        ]
        run_case(items, clusters)


def test_contention_steady_state_no_double_count():
    """Bindings that KEEP their previous assignment consume nothing new:
    a chunk of unchanged steady-state bindings must not drain the snapshot
    (regression: wave accounting once charged full rep, so re-scheduling
    unchanged bindings went spuriously unschedulable)."""
    from karmada_tpu.models.work import TargetCluster

    clusters = [mk_cluster("m1", cpu_milli=10_000, mem_units=100, pods=100)]
    # snapshot already accounts the running replicas
    consume(clusters[0], 8, 1000, 1)
    items = []
    for b in range(2):
        spec, st = mk_binding(b, replicas=4, cpu_milli=1000, mem_units=1)
        spec.clusters = [TargetCluster(name="m1", replicas=4)]
        st.last_scheduled_time = 100.0
        items.append((spec, st))
    run_case(items, clusters)
    want = serial_one_at_a_time(items, clusters)
    # both keep their 4 replicas; nothing is newly consumed, nothing fails
    assert all(not isinstance(w, Exception) for w in want)
    assert [{t.name: t.replicas for t in w} for w in want] == [{"m1": 4}] * 2


def test_contention_scale_up_delta_only():
    """A scale-up charges only the delta; the kept part is free."""
    from karmada_tpu.models.work import TargetCluster

    clusters = [mk_cluster("m1", cpu_milli=10_000, mem_units=100, pods=100)]
    consume(clusters[0], 4, 1000, 1)  # 4 running -> 6 cpu-slots left
    items = []
    for b in range(3):
        spec, st = mk_binding(b, replicas=4, cpu_milli=1000, mem_units=1)
        if b == 0:
            spec.clusters = [TargetCluster(name="m1", replicas=2)]  # +2 delta
            st.last_scheduled_time = 100.0
        items.append((spec, st))
    run_case(items, clusters)


def test_waves_one_reproduces_shared_snapshot():
    """waves=1 is the documented shared-snapshot mode: every binding sees
    full capacity (the round-2 behavior), so all four fit 'on paper'."""
    clusters = [mk_cluster("m1", cpu_milli=10_000, mem_units=100, pods=100)]
    items = [mk_binding(b, replicas=4, cpu_milli=1000, mem_units=1) for b in range(4)]
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator, pad_bindings=False)
    rep, sel, status = solve(batch, waves=1)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    assert all(not isinstance(g, Exception) for g in got)


def test_intermediate_wave_counts_monotone():
    """waves=2 on 4 bindings: pairs share a snapshot; second pair sees the
    first pair's combined usage."""
    clusters = [mk_cluster("m1", cpu_milli=10_000, mem_units=100, pods=100)]
    items = [mk_binding(b, replicas=4, cpu_milli=1000, mem_units=1) for b in range(4)]
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator, pad_bindings=False)
    rep, sel, status = solve(batch, waves=2)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    # wave 1 (b0, b1) both fit vs fresh snapshot; wave 2 sees 8 replicas
    # consumed -> only 2 cpu-capacity left -> 4-replica asks are unschedulable
    assert not isinstance(got[0], Exception) and not isinstance(got[1], Exception)
    assert isinstance(got[2], serial.UnschedulableError)
    assert isinstance(got[3], serial.UnschedulableError)


def _divergence(B, C, waves):
    """(ok_w, ok_B, n_differing, totals_equal) for waves vs waves=B on the
    bench scenario mix under tight capacity (demand >> fleet capacity)."""
    import numpy as np

    import bench

    rng = random.Random(0)
    clusters = bench.build_fleet(rng, C)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, B, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    rep_w, _, st_w = solve(batch, waves=waves)
    rep_b, _, st_b = solve(batch, waves=B)
    ok_w, ok_b = int((st_w == 0).sum()), int((st_b == 0).sum())
    n_diff = int(((rep_w != rep_b).any(axis=1) | (st_w != st_b)).sum())
    both = (st_w == 0) & (st_b == 0)
    totals_equal = bool(
        (rep_w[both].sum(axis=1) == rep_b[both].sum(axis=1)).all())
    return ok_w, ok_b, n_diff, totals_equal


def _assert_divergence_bounds(B, ok_w, ok_b, n_diff, totals_equal):
    """The quantified within-wave contention race (VERDICT r3 weak #5).

    Production waves=8 diverges from the serial-equivalent waves=B in a
    BOUNDED, characterized way under capacity pressure:
      * ok_w >= ok_b: coarser waves price against a less-decremented
        snapshot, so they only ever schedule MORE (optimism, never loss) —
        the monotonicity test above asserts the full chain;
      * the optimism is bounded (<= 15% of the chunk on the bench mix at
        ~3x overcommitted demand — measured 7% at B=1024);
      * every binding scheduled by BOTH gets its exact replica total in
        both (divergence moves placement, never workload size);
      * assignment-shape divergence (different target maps, mostly from
        dynamic weights seeing different snapshots) stays a bounded
        minority of the chunk (measured 18% at B=1024 under ~3x
        overcommit; bound 35%).
    """
    assert ok_w >= ok_b, (ok_w, ok_b)
    assert ok_w - ok_b <= 0.15 * B, (ok_w, ok_b)
    assert totals_equal
    assert n_diff <= 0.35 * B, n_diff


def test_wave_contention_divergence_bounded():
    B = 1024
    ok_w, ok_b, n_diff, totals_equal = _divergence(B, 64, waves=8)
    _assert_divergence_bounds(B, ok_w, ok_b, n_diff, totals_equal)


@pytest.mark.skipif(os.environ.get("KARMADA_TPU_SOAK") != "1",
                    reason="full-chunk divergence sweep is opt-in (slow)")
def test_wave_contention_divergence_full_chunk():
    """The production chunk size itself: 4096 bindings."""
    B = 4096
    ok_w, ok_b, n_diff, totals_equal = _divergence(B, 128, waves=8)
    _assert_divergence_bounds(B, ok_w, ok_b, n_diff, totals_equal)


def test_capacity_carry_across_batches_matches_combined_solve():
    """Cross-batch capacity continuity: solving batch A (with_used) and
    then batch B with A's carry (used0) must equal solving A+B as ONE
    batch under the same wave order — the accumulators transport the
    consumed-capacity state exactly."""
    import numpy as np

    import bench
    from karmada_tpu.ops.solver import solve_compact

    rng = random.Random(2)
    clusters = bench.build_fleet(rng, 32)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 64, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)

    a_items, b_items = items[:32], items[32:]

    # combined reference: one batch, one binding per wave (exact order)
    batch_ab = tensors.encode_batch(items, cindex, est)
    i_ab, v_ab, s_ab, _ = solve_compact(batch_ab, waves=64)
    combined = tensors.decode_compact(batch_ab, i_ab, v_ab, s_ab)

    # split: A first (collect carry), then B against A's residual
    batch_a = tensors.encode_batch(a_items, cindex, est)
    _, _, _, _, used = solve_compact(batch_a, waves=32, with_used=True)
    batch_b = tensors.encode_batch(b_items, cindex, est)
    used0 = tensors.remap_used(used, batch_a, batch_b)
    i_b, v_b, s_b, _ = solve_compact(batch_b, waves=32, used0=used0)
    split_b = tensors.decode_compact(batch_b, i_b, v_b, s_b)

    for j in range(len(b_items)):
        want = combined[32 + j]
        got = split_b[j]
        if isinstance(want, Exception):
            assert isinstance(got, type(want)), (j, want, got)
            continue
        assert not isinstance(got, Exception), (j, got)
        assert ({t.name: t.replicas for t in got}
                == {t.name: t.replicas for t in want}), j


def test_carry_state_survives_vocabulary_gaps():
    """CarryState (chained transport): consumption of a resource absent
    from an INTERMEDIATE batch's vocabulary must survive to a later batch
    that requests it (pairwise remap_used would drop it)."""
    import numpy as np

    from karmada_tpu.ops.solver import solve_compact

    clusters = [mk_cluster("m1", cpu_milli=10**9, mem_units=10, pods=10**6)]
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()

    def mem_binding(b, replicas):
        spec, st = mk_binding(b, replicas=replicas, cpu_milli=10, mem_units=1)
        return spec, st

    def cpu_binding(b, replicas):
        spec, st = mk_binding(b, replicas=replicas, cpu_milli=10, mem_units=0)
        spec.replica_requirements.resource_request.pop("memory")
        return spec, st

    state = tensors.CarryState()

    def run(items, waves=1):
        batch = tensors.encode_batch(items, cindex, est)
        used0 = state.used0_for(batch)
        i, v, s, _n, used = solve_compact(batch, waves=waves, used0=used0,
                                          with_used=True)
        state.absorb(batch, used, used0)
        return tensors.decode_compact(batch, i, v, s)

    # chunk 1 consumes 8 of the 10 memory units
    r1 = run([mem_binding(0, 8)])
    assert not isinstance(r1[0], Exception)
    # chunk 2's vocabulary has NO memory resource at all
    r2 = run([cpu_binding(1, 5)])
    assert not isinstance(r2[0], Exception)
    assert "memory" in state.milli  # survived the gap
    # chunk 3 wants 8 memory units: only 2 remain -> honest failure
    r3 = run([mem_binding(2, 8)])
    assert isinstance(r3[0], serial.UnschedulableError), r3[0]
