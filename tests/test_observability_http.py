"""Observability HTTP endpoint (profileflag/metrics-server analog)."""

from __future__ import annotations

import json
import urllib.request

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.utils.httpserve import ObservabilityServer


def fetch(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def test_endpoints_serve_metrics_health_and_state():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    ready = {"ok": True}
    srv = ObservabilityServer(store=cp.store,
                              ready_probe=lambda: ready["ok"])
    base = srv.start()
    try:
        status, body = fetch(base + "/metrics")
        assert status == 200 and "karmada_" in body
        status, body = fetch(base + "/healthz")
        assert status == 200 and body == "ok"
        status, body = fetch(base + "/readyz")
        assert status == 200
        ready["ok"] = False
        try:
            status, _ = fetch(base + "/readyz")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 503
        status, body = fetch(base + "/debug/state")
        state = json.loads(body)
        assert status == 200 and state["objects_by_kind"].get("Cluster") == 1
    finally:
        srv.stop()
