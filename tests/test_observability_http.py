"""Observability HTTP endpoint (profileflag/metrics-server analog)."""

from __future__ import annotations

import json
import urllib.request

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.utils.httpserve import ObservabilityServer


def fetch(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def test_device_solver_stage_histograms_visible_at_metrics():
    """A production operator of the batched design watches per-stage solver
    latency (reference pkg/scheduler/metrics/metrics.go:93-142 publishes
    per-step histograms): after one served cycle through the DEVICE
    backend, /metrics must expose every pipeline stage — Encode, H2D
    (dispatch), Solve (device wait), D2H (result copy), Decode."""
    cp = ControlPlane(backend="device")
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(),
        ),
    ))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 2, "template": {"spec": {"containers": [
                  {"name": "a", "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()
    assert cp.store.get("ResourceBinding", "default", "app-deployment").spec.clusters

    srv = ObservabilityServer(store=cp.store)
    base = srv.start()
    try:
        _, body = fetch(base + "/metrics")
    finally:
        srv.stop()
    for stage in ("Encode", "H2D", "Solve", "D2H", "Decode"):
        needle = ("karmada_scheduler_scheduling_algorithm_duration_seconds_count"
                  f'{{schedule_step="{stage}"}}')
        assert needle in body, f"stage {stage} missing from /metrics"


def test_endpoints_serve_metrics_health_and_state():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    ready = {"ok": True}
    srv = ObservabilityServer(store=cp.store,
                              ready_probe=lambda: ready["ok"])
    base = srv.start()
    try:
        status, body = fetch(base + "/metrics")
        assert status == 200 and "karmada_" in body
        status, body = fetch(base + "/healthz")
        assert status == 200 and body == "ok"
        status, body = fetch(base + "/readyz")
        assert status == 200
        ready["ok"] = False
        try:
            status, _ = fetch(base + "/readyz")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 503
        status, body = fetch(base + "/debug/state")
        state = json.loads(body)
        assert status == 200 and state["objects_by_kind"].get("Cluster") == 1
    finally:
        srv.stop()
