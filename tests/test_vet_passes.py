"""Per-pass vet fixtures: each pass catches its seeded violation and
passes on the fixed variant; waiver + CLI + armed-runtime-guard behavior.
"""

import json
import textwrap

import numpy as np
import pytest

from karmada_tpu.analysis.vet import run_vet


def _vet(tmp_path, name, src, extra=None):
    (tmp_path / name).write_text(textwrap.dedent(src))
    for fname, fsrc in (extra or {}).items():
        (tmp_path / fname).write_text(textwrap.dedent(fsrc))
    return run_vet([str(tmp_path)])


# -- pass 1: trace-safety ----------------------------------------------------

TRACE_BAD = """
    from functools import partial
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _helper(x):
        while jnp.any(x > 0):
            x = x - 1
        return x

    def _core(x):
        if jnp.sum(x) > 0:
            x = x + 1
        y = float(jnp.max(x))
        z = np.asarray(x)
        w = jnp.zeros((4,))
        return _helper(x)

    solve = partial(jax.jit, static_argnames=())(_core)
"""

TRACE_FIXED = """
    from functools import partial
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _core(x, flag: bool):
        if flag:  # static python bool: fine
            x = x + 1
        x = jnp.where(jnp.sum(x) > 0, x + 1, x)
        w = jnp.zeros((4,), jnp.int64)
        n = jnp.arange(8, dtype=jnp.int32)
        return x

    solve = partial(jax.jit, static_argnames=("flag",))(_core)
"""


def test_trace_safety_catches_seeded(tmp_path):
    report = _vet(tmp_path, "mod.py", TRACE_BAD)
    rules = sorted(f.rule for f in report.findings)
    assert "trace-branch" in rules
    assert "trace-host-sync" in rules
    assert "trace-weak-int" in rules
    # the transitive closure reached _helper's while-loop too
    branch_lines = [f.line for f in report.findings
                    if f.rule == "trace-branch"]
    assert len(branch_lines) == 2


def test_trace_safety_clean_on_fixed(tmp_path):
    report = _vet(tmp_path, "mod.py", TRACE_FIXED)
    assert report.clean, report.render_text()


def test_trace_safety_ignores_host_code(tmp_path):
    # the same constructs OUTSIDE jit code are host-side and legal
    report = _vet(tmp_path, "mod.py", """
        import numpy as np
        import jax.numpy as jnp

        def host(x):
            if jnp.sum(x) > 0:
                return np.asarray(x)
            return float(jnp.max(x))
    """)
    assert report.clean, report.render_text()


def test_trace_safety_decorator_and_vmap_roots(tmp_path):
    report = _vet(tmp_path, "mod.py", """
        from functools import partial
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("G",))
        def phase(x, *, G):
            return jnp.zeros((G,))

        def _one(x):
            return jnp.arange(4)

        one_v = jax.vmap(_one)
    """)
    assert sorted(f.rule for f in report.findings) == [
        "trace-weak-int", "trace-weak-int"]


def test_trace_safety_cross_module_basename_collision(tmp_path):
    # two helpers.py in different subpackages: the closure must resolve
    # the from-import to the RIGHT one by path suffix, not basename
    pkg = tmp_path / "pkg"
    (pkg / "a").mkdir(parents=True)
    (pkg / "b").mkdir()
    for d in (pkg, pkg / "a", pkg / "b"):
        (d / "__init__.py").write_text("")
    (pkg / "a" / "helpers.py").write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def h(x):
            return jnp.zeros((4,), jnp.int64)  # clean
    """))
    (pkg / "b" / "helpers.py").write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def h(x):
            return jnp.zeros((4,))  # weak dtype: must be found
    """))
    (pkg / "b" / "core.py").write_text(textwrap.dedent("""
        import jax
        from pkg.b.helpers import h

        def _core(x):
            return h(x)

        solve = jax.jit(_core)
    """))
    report = run_vet([str(tmp_path)])
    assert [f.rule for f in report.findings] == ["trace-weak-int"]
    assert report.findings[0].file.endswith("b/helpers.py")


# -- pass 2: dtype-contract --------------------------------------------------

# indented to match the in-test fixture literals (textwrap.dedent runs on
# the concatenation)
DTYPE_TABLE = """
        FIELD_DTYPES = {"name_rank": "int64", "b_valid": "bool",
                        "prev_val": "int32"}
"""


def test_dtype_contract_catches_drift(tmp_path):
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(C):
            name_rank = np.zeros(C, np.int32)   # drift: table says int64
            b_valid = np.zeros(C)               # missing dtype -> f64
            prev_val = np.zeros((C, 4), np.int32)  # correct
            return name_rank, b_valid, prev_val
    """)
    assert len(report.findings) == 2
    assert all(f.rule == "dtype-contract" for f in report.findings)


def test_dtype_contract_clean_on_fixed(tmp_path):
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(C, other):
            name_rank = np.zeros(C, np.int64)
            b_valid = np.zeros(C, bool)
            prev_val = np.asarray(other, np.int32)
            local = np.zeros(C)  # not a declared field: unchecked
            return name_rank, b_valid, prev_val, local
    """)
    assert report.clean, report.render_text()


def test_dtype_contract_checks_astype_and_attributes(tmp_path):
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(batch, raw):
            batch.name_rank = raw.astype(np.int32)  # attribute drift
            return batch
    """)
    assert [f.rule for f in report.findings] == ["dtype-contract"]


# -- pass 3: spec-coverage ---------------------------------------------------

SPEC_FIELDS = """
    import numpy as np
    from dataclasses import dataclass, field

    @dataclass
    class SolverBatch:
        B: int
        avail_milli: np.ndarray
        region_id: np.ndarray = field(default=None)
        route: np.ndarray = field(default=None)
        names: list = None
"""


def test_spec_coverage_catches_missing_and_stale(tmp_path):
    report = _vet(tmp_path, "tensors.py", SPEC_FIELDS, extra={
        "meshing.py": """
            HOST_ONLY_FIELDS = frozenset({"route"})

            def shard_specs():
                return {"avail_milli": 1, "stale_key": 2}
        """})
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 2
    assert "region_id" in msgs[0]   # missing spec entry
    assert "stale_key" in msgs[1]   # spec entry with no field


def test_spec_coverage_clean_on_fixed(tmp_path):
    report = _vet(tmp_path, "tensors.py", SPEC_FIELDS, extra={
        "meshing.py": """
            HOST_ONLY_FIELDS = frozenset({"route"})

            def shard_specs():
                return {"avail_milli": 1, "region_id": 2}
        """})
    assert report.clean, report.render_text()


# -- pass 4: lock-discipline -------------------------------------------------

LOCK_BAD = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self._ring = []  # guarded-by: _lock
            self._count = 0  # guarded-by: _lock

        def good(self, t):
            with self._lock:
                self._ring.append(t)
                self._count += 1

        def bad_call(self, t):
            self._ring.append(t)

        def bad_rebind(self):
            self._ring = []

        def bad_item(self, i, t):
            self._ring[i] = t

        def bad_aug(self):
            self._count += 1
"""


def test_lock_discipline_catches_seeded(tmp_path):
    report = _vet(tmp_path, "mod.py", LOCK_BAD)
    assert len(report.findings) == 4
    assert all(f.rule == "guarded-by" for f in report.findings)


def test_lock_discipline_clean_on_fixed(tmp_path):
    report = _vet(tmp_path, "mod.py", """
        import threading

        class Ring:
            def __init__(self):
                self._lock = threading.Lock()
                self._ring = []  # guarded-by: _lock

            def push(self, t):
                with self._lock:
                    self._ring.append(t)

            def reset(self):
                with self._lock:
                    self._ring = []

            def read(self):
                return list(self._ring)  # reads are not checked
    """)
    assert report.clean, report.render_text()


def test_lock_discipline_nested_def_resets_context(tmp_path):
    # a `with` around a def does NOT guard the deferred body
    report = _vet(tmp_path, "mod.py", """
        import threading

        class Ring:
            def __init__(self):
                self._lock = threading.Lock()
                self._ring = []  # guarded-by: _lock

            def schedule(self):
                with self._lock:
                    def later():
                        self._ring.append(1)
                    return later
    """)
    assert [f.rule for f in report.findings] == ["guarded-by"]


def test_lock_discipline_module_level_and_mutators(tmp_path):
    report = _vet(tmp_path, "mod.py", """
        import threading

        _LOCK = threading.Lock()
        _LAST: dict = {}  # guarded-by: _LOCK

        class Q:
            def __init__(self):
                self._qlock = threading.Lock()
                # guarded-by: _qlock; mutators: push
                self.queue = object()

            def ok(self):
                with self._qlock:
                    self.queue.push(1)

            def bad(self):
                self.queue.push(1)

            def fine_read(self):
                return self.queue.depths()

        def ok():
            with _LOCK:
                _LAST.update(x=1)

        def bad():
            _LAST["x"] = 2
    """)
    assert len(report.findings) == 2
    lines = sorted(f.line for f in report.findings)
    assert all(f.rule == "guarded-by" for f in report.findings)


# -- waivers -----------------------------------------------------------------

def test_waiver_suppresses_and_is_counted(tmp_path):
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(C):
            # vet: ignore[dtype-contract] fixture: deliberately int32
            name_rank = np.zeros(C, np.int32)
            return name_rank
    """)
    assert report.clean
    assert len(report.waivers) == 1
    w = report.waivers[0]
    assert w.rule == "dtype-contract"
    assert "deliberately" in w.justification


def test_bare_waiver_is_a_finding(tmp_path):
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(C):
            name_rank = np.zeros(C, np.int32)  # vet: ignore[dtype-contract]
            return name_rank
    """)
    rules = sorted(f.rule for f in report.findings)
    # the unjustified waiver suppresses nothing AND is itself reported
    assert rules == ["dtype-contract", "waiver-syntax"]


# -- CLI ---------------------------------------------------------------------

def test_cli_vet_json_and_exit_codes(tmp_path, capsys):
    from karmada_tpu import cli

    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "tensors.py").write_text(textwrap.dedent(DTYPE_TABLE + """
        import numpy as np
        name_rank = np.zeros(4, np.int32)
    """))
    rc = cli.main(["vet", str(bad), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["clean"] is False
    assert out["counts"]["findings"] == 1
    f = out["findings"][0]
    assert f["rule"] == "dtype-contract" and f["line"] > 0

    good = tmp_path / "good"
    good.mkdir()
    (good / "ok.py").write_text("x = 1\n")
    assert cli.main(["vet", str(good), "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["clean"] is True

    # unknown rule filter: usage error, distinct exit code
    assert cli.main(["vet", str(good), "--rules", "nope"]) == 2


def test_cli_vet_rule_filter(tmp_path, capsys):
    from karmada_tpu import cli

    (tmp_path / "tensors.py").write_text(textwrap.dedent(DTYPE_TABLE + """
        import numpy as np
        name_rank = np.zeros(4, np.int32)
    """))
    rc = cli.main(["vet", str(tmp_path), "--rules", "trace-branch",
                   "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["clean"] is True  # dtype finding filtered out


def test_cli_vet_nonexistent_path_is_usage_error(tmp_path, capsys):
    # a typo'd path must be exit 2, never a 0-file "clean" pass
    from karmada_tpu import cli

    rc = cli.main(["vet", str(tmp_path / "no_such_dir")])
    err = capsys.readouterr().err
    assert rc == 2 and "no such path" in err


def test_rule_filter_keeps_all_waivers(tmp_path):
    # the waiver population is an audit surface: --rules never hides it
    report = _vet(tmp_path, "tensors.py", DTYPE_TABLE + """
        import numpy as np

        def build(C):
            # vet: ignore[dtype-contract] fixture: deliberately int32
            name_rank = np.zeros(C, np.int32)
            return name_rank
    """)
    filtered = run_vet([str(tmp_path)], rules=["trace-branch"])
    assert filtered.clean
    assert len(filtered.waivers) == 1
    assert filtered.waivers[0].rule == "dtype-contract"


# -- armed runtime guards ----------------------------------------------------

def _mini_batch():
    from karmada_tpu.models.cluster import (
        Cluster, ClusterSpec, ClusterStatus, ResourceSummary,
    )
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import Placement
    from karmada_tpu.models.work import (
        ResourceBindingSpec, ResourceBindingStatus,
    )
    from karmada_tpu.ops import tensors as T
    from karmada_tpu.utils.quantity import Quantity

    clusters = [
        Cluster(
            metadata=ObjectMeta(name=f"m{i}"),
            spec=ClusterSpec(),
            status=ClusterStatus(resource_summary=ResourceSummary(
                allocatable={"cpu": Quantity.from_milli(64000),
                             "pods": Quantity.from_units(110)},
                allocated={},
            )),
        )
        for i in range(2)
    ]
    items = [(ResourceBindingSpec(placement=Placement(), replicas=3),
              ResourceBindingStatus())]
    return T.encode_batch(items, T.ClusterIndex.build(clusters))


def test_guards_pass_on_real_batch_and_catch_drift():
    from karmada_tpu.analysis import guards

    batch = _mini_batch()
    guards.check_batch(batch)  # canonical tables match reality
    batch.name_rank = batch.name_rank.astype(np.int32)
    with pytest.raises(guards.InvariantViolation, match="name_rank"):
        guards.check_batch(batch)


def test_guards_armed_through_solver_dispatch():
    from karmada_tpu.analysis import guards
    from karmada_tpu.ops import solver

    guards.arm()
    try:
        batch = _mini_batch()
        res = solver.solve_compact(batch, waves=1)  # clean: no raise
        assert res[3] >= 0
        batch.replicas = batch.replicas.astype(np.int32)
        with pytest.raises(guards.InvariantViolation, match="replicas"):
            solver.solve_compact(batch, waves=1)
    finally:
        guards.arm(False)


def test_guards_d2h_checks():
    from karmada_tpu.analysis import guards

    ok_idx = np.array([0, 3, -1], np.int32)
    ok_val = np.array([2, 1, 0], np.int32)
    ok_st = np.zeros(2, np.int32)
    guards.check_d2h(ok_idx, ok_val, ok_st, dense_nnz=16)
    with pytest.raises(guards.InvariantViolation, match="out of range"):
        guards.check_d2h(np.array([99], np.int32), ok_val[:1], ok_st,
                         dense_nnz=16)
    with pytest.raises(guards.InvariantViolation, match="status"):
        guards.check_d2h(ok_idx, ok_val, np.array([7], np.int32),
                         dense_nnz=16)
    with pytest.raises(guards.InvariantViolation, match="int32"):
        guards.check_d2h(ok_idx.astype(np.int64), ok_val, ok_st,
                         dense_nnz=16)


def test_guards_disarmed_is_noop():
    from karmada_tpu.analysis import guards

    assert not guards.armed()
    batch = _mini_batch()
    batch.replicas = batch.replicas.astype(np.int32)
    from karmada_tpu.ops import solver

    # disarmed: the drifted batch still dispatches (pre-vet behavior)
    res = solver.solve_compact(batch, waves=1)
    assert res[3] >= 0


# -- pass 6: exception-hygiene ----------------------------------------------

EXC_BAD = """
    def swallow():
        try:
            risky()
        except Exception:
            pass

    def swallow_bare():
        try:
            risky()
        except:  # noqa: E722
            return None
"""

EXC_FIXED = """
    METRIC = object()

    def reraises():
        try:
            risky()
        except Exception as e:
            raise RuntimeError("wrapped") from e

    def counts():
        try:
            risky()
        except Exception:
            FAULTS.inc(kind="x")

    def typed_is_fine():
        try:
            risky()
        except ValueError:
            pass
"""


def test_exception_hygiene_catches_seeded(tmp_path):
    report = _vet(tmp_path, "mod.py", EXC_BAD)
    lines = [f.line for f in report.findings
             if f.rule == "exception-hygiene"]
    assert len(lines) == 2  # the blanket AND the bare except


def test_exception_hygiene_clean_on_fixed(tmp_path):
    report = _vet(tmp_path, "mod.py", EXC_FIXED)
    assert [f for f in report.findings
            if f.rule == "exception-hygiene"] == []


def test_exception_hygiene_waiver(tmp_path):
    report = _vet(tmp_path, "mod.py", """
        def swallow():
            try:
                risky()
            # vet: ignore[exception-hygiene] error body answers the peer
            except Exception:
                return None
    """)
    assert [f for f in report.findings
            if f.rule == "exception-hygiene"] == []
    assert any(w.rule == "exception-hygiene" for w in report.waivers)


# -- metric-docs (pass 7) -----------------------------------------------------

METRIC_SRC = """
    from karmada_tpu.utils.metrics import REGISTRY
    DOCUMENTED = REGISTRY.counter(
        "karmada_fixture_documented_total", "help text")
    UNDOCUMENTED = REGISTRY.counter(
        "karmada_fixture_ghost_total", "help text")
"""


def _docs_tree(tmp_path, doc_text, src=METRIC_SRC):
    """A fixture tree shaped like the package: the registry home
    (utils/metrics.py — the whole-package marker), a registration
    module, and docs/OBSERVABILITY.md one level above."""
    import textwrap as _tw

    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "metrics.py").write_text("REGISTRY = None\n")
    (pkg / "mod.py").write_text(_tw.dedent(src))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "OBSERVABILITY.md").write_text(_tw.dedent(doc_text))
    return run_vet([str(pkg)])


def test_metric_docs_catches_undocumented_and_stale(tmp_path):
    report = _docs_tree(tmp_path, """
        # Metrics
        * `karmada_fixture_documented_total{kind}` — documented
        * `karmada_fixture_stale_total` — registered by nothing
    """)
    msgs = {f.message for f in report.findings if f.rule == "metric-docs"}
    assert any("karmada_fixture_ghost_total" in m
               and "not catalogued" in m for m in msgs)
    assert any("karmada_fixture_stale_total" in m
               and "stale" in m for m in msgs)
    assert not any("karmada_fixture_documented_total" in m for m in msgs)
    # the stale finding anchors at the DOC file/line
    stale = [f for f in report.findings
             if f.rule == "metric-docs" and "stale" in f.message]
    assert stale[0].file.endswith("OBSERVABILITY.md")


def test_metric_docs_clean_on_fixed_and_brace_forms(tmp_path):
    # name expansion + label braces both resolve; a doc-side waiver
    # covers the deliberately-external row
    report = _docs_tree(tmp_path, """
        * `karmada_fixture_{documented,ghost}_total{kind=a|b}` — both
        * `karmada_fixture_external_total` <!-- metric-docs: ok scraped from the agent -->
    """)
    assert [f for f in report.findings if f.rule == "metric-docs"] == []


def test_metric_docs_code_side_waiver_and_missing_doc(tmp_path):
    import textwrap as _tw

    report = _docs_tree(tmp_path, """
        * `karmada_fixture_documented_total`
    """, src="""
        from karmada_tpu.utils.metrics import REGISTRY
        DOCUMENTED = REGISTRY.counter(
            "karmada_fixture_documented_total", "help text")
        # vet: ignore[metric-docs] internal-only debugging series
        UNDOC = REGISTRY.counter(
            "karmada_fixture_ghost_total", "help text")
    """)
    assert [f for f in report.findings if f.rule == "metric-docs"] == []
    assert any(w.rule == "metric-docs" for w in report.waivers)


def test_metric_docs_missing_doc_is_a_finding(tmp_path):
    """No docs/OBSERVABILITY.md anywhere above the scanned tree: one
    actionable finding, never a silently-vacuous gate.  (Its own
    tmp_path — a sibling doc from another fixture tree must not be
    found by the walk-up.)"""
    import textwrap as _tw

    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "utils" / "metrics.py").write_text("REGISTRY = None\n")
    (pkg / "mod.py").write_text(_tw.dedent(METRIC_SRC))
    report = run_vet([str(pkg)])
    assert any(f.rule == "metric-docs" and "not found" in f.message
               for f in report.findings)


def test_metric_docs_skips_partial_scans(tmp_path):
    """Vetting a single module (no utils/metrics.py in the scanned set)
    must not judge doc parity — partial scans would report the whole
    doc as stale."""
    import textwrap as _tw

    (tmp_path / "mod.py").write_text(_tw.dedent(METRIC_SRC))
    report = run_vet([str(tmp_path / "mod.py")])
    assert [f for f in report.findings if f.rule == "metric-docs"] == []


# -- event-reasons (pass 8) ---------------------------------------------------

EVENTS_TAXONOMY = """
    REASON_FIXTURE_GOOD = "FixtureGood"
    REASON_FIXTURE_GHOST = "FixtureGhost"
"""


def _events_tree(tmp_path, doc_text, src, taxonomy=EVENTS_TAXONOMY):
    """A fixture tree shaped like the package: the taxonomy home
    (obs/events.py — the whole-package marker), an emitting module, and
    docs/OBSERVABILITY.md one level above."""
    import textwrap as _tw

    pkg = tmp_path / "pkg"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "obs" / "events.py").write_text(_tw.dedent(taxonomy))
    (pkg / "mod.py").write_text(_tw.dedent(src))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "OBSERVABILITY.md").write_text(_tw.dedent(doc_text))
    return run_vet([str(pkg)])


def test_event_reasons_catches_literal_and_computed_reasons(tmp_path):
    report = _events_tree(tmp_path, "| `FixtureGood` | `FixtureGhost` |", """
        from karmada_tpu.utils import events as ev

        def go(recorder, rb, ready):
            recorder.event(rb, ev.TYPE_WARNING, "AdHocReason", "msg")
            ev.emit_key(("ns", "n"), ev.TYPE_NORMAL,
                        ev.REASON_FIXTURE_GOOD if ready else "Other", "msg")
    """)
    msgs = [f.message for f in report.findings if f.rule == "event-reasons"]
    assert len(msgs) == 2
    assert any("string literal" in m for m in msgs)
    assert any("expression" in m for m in msgs)


def test_event_reasons_clean_on_constants_and_catalogued_doc(tmp_path):
    report = _events_tree(tmp_path, """
        ## Reason catalog
        | `FixtureGood` | fine |
        | `FixtureGhost` | also catalogued |
    """, """
        from karmada_tpu.utils import events as ev

        def go(recorder, rb):
            recorder.event(rb, ev.TYPE_NORMAL, ev.REASON_FIXTURE_GOOD, "m")
            ev.emit(ev.SCHEDULER_REF, ev.TYPE_NORMAL,
                    ev.REASON_FIXTURE_GHOST, "m", origin="x")
            ev.emit_key(("a", "b"), ev.TYPE_NORMAL,
                        reason=ev.REASON_FIXTURE_GOOD, message="kw form")
    """)
    assert [f for f in report.findings if f.rule == "event-reasons"] == []


def test_event_reasons_catches_uncatalogued_constant(tmp_path):
    # FixtureGhost is declared in the taxonomy home but missing from the
    # doc catalog: the doc-parity leg reports it at the declaration
    report = _events_tree(tmp_path, "only `FixtureGood` is here", """
        from karmada_tpu.utils import events as ev

        def go(recorder, rb):
            recorder.event(rb, ev.TYPE_NORMAL, ev.REASON_FIXTURE_GOOD, "m")
    """)
    bad = [f for f in report.findings if f.rule == "event-reasons"]
    assert len(bad) == 1
    assert "FixtureGhost" in bad[0].message
    assert bad[0].file.endswith("events.py")


def test_event_reasons_waiver_and_partial_scan(tmp_path):
    import textwrap as _tw

    # a waived literal call site is a waiver, not a finding
    report = _events_tree(tmp_path, "| `FixtureGood` | `FixtureGhost` |", """
        from karmada_tpu.utils import events as ev

        def go(recorder, rb):
            # vet: ignore[event-reasons] fixture exercising the waiver channel
            recorder.event(rb, ev.TYPE_NORMAL, "Literal", "m")
    """)
    assert [f for f in report.findings if f.rule == "event-reasons"] == []
    assert any(w.rule == "event-reasons" for w in report.waivers)
    # partial scan (no obs/events.py in view): the doc-parity leg must
    # not run, only call-site findings
    (tmp_path / "solo.py").write_text(_tw.dedent("""
        def go(recorder, rb):
            recorder.event(rb, "Normal", "Literal", "m")
    """))
    solo = run_vet([str(tmp_path / "solo.py")])
    assert all("catalogued" not in f.message for f in solo.findings
               if f.rule == "event-reasons")
    assert any(f.rule == "event-reasons" for f in solo.findings)


# -- incident-plane seeds (ISSUE-20) ------------------------------------------
# pin the NEW names through both doc-parity passes: the three
# karmada_incident* families and the SafetyViolation / IncidentCaptured
# reasons must stay catalogued in docs/OBSERVABILITY.md — renaming or
# dropping a row turns these fixtures into real-package findings too


def test_metric_docs_incident_families_seeded(tmp_path):
    doc = """
        * `karmada_incidents_total{trigger}` — bundles captured
        * `karmada_incidents_suppressed_total{trigger}` — cooldown drops
        * `karmada_incident_capture_seconds` — capture wall time
    """
    src = """
        from karmada_tpu.utils.metrics import REGISTRY
        INCIDENTS = REGISTRY.counter(
            "karmada_incidents_total", "help", ("trigger",))
        SUPPRESSED = REGISTRY.counter(
            "karmada_incidents_suppressed_total", "help", ("trigger",))
        CAPTURE = REGISTRY.histogram(
            "karmada_incident_capture_seconds", "help")
    """
    report = _docs_tree(tmp_path / "clean", doc, src=src)
    assert [f for f in report.findings if f.rule == "metric-docs"] == []
    # dropping the catalog rows turns all three into findings
    report = _docs_tree(tmp_path / "bare",
                        "# Metrics\n(no incident rows)\n", src=src)
    msgs = {f.message for f in report.findings if f.rule == "metric-docs"}
    for name in ("karmada_incidents_total",
                 "karmada_incidents_suppressed_total",
                 "karmada_incident_capture_seconds"):
        assert any(name in m for m in msgs), (name, msgs)


def test_event_reasons_incident_reasons_seeded(tmp_path):
    taxonomy = """
        REASON_SAFETY_VIOLATION = "SafetyViolation"
        REASON_INCIDENT_CAPTURED = "IncidentCaptured"
    """
    src = """
        from karmada_tpu.utils import events as ev

        def go():
            ev.emit_key(("ns", "b0"), ev.TYPE_WARNING,
                        ev.REASON_SAFETY_VIOLATION, "invariant violated",
                        origin="chaos-audit")
            ev.emit(ev.SCHEDULER_REF, ev.TYPE_WARNING,
                    ev.REASON_INCIDENT_CAPTURED, "bundle captured",
                    origin="incidents")
    """
    report = _events_tree(tmp_path / "clean", """
        ## Reason catalog
        | `SafetyViolation` | chaos auditor invariant breach |
        | `IncidentCaptured` | incident bundle landed |
    """, src, taxonomy=taxonomy)
    assert [f for f in report.findings if f.rule == "event-reasons"] == []
    # an uncatalogued incident reason is a finding at the taxonomy home
    report = _events_tree(tmp_path / "bare",
                          "only `SafetyViolation` is here",
                          src, taxonomy=taxonomy)
    bad = [f for f in report.findings if f.rule == "event-reasons"]
    assert len(bad) == 1 and "IncidentCaptured" in bad[0].message
