"""Failure -> eviction -> reschedule loop (reference call stack 3.5), plus
descheduler rebalancing, namespace sync, and dependency distribution."""

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    FailoverBehavior,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding, Work


def dynamic_policy(name="pp", propagate_deps=False, failover=None):
    return PropagationPolicy(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
            propagate_deps=propagate_deps,
            failover=failover,
        ),
    )


def deployment(replicas=6, volumes=None):
    spec = {"containers": [{"name": "app", "image": "app:1",
                            "resources": {"requests": {"cpu": "500m",
                                                       "memory": "1Gi"}}}]}
    if volumes:
        spec["volumes"] = volumes
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "app", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": spec}},
    }


def test_cluster_failure_evicts_and_reschedules():
    cp = ControlPlane(eviction_grace_period_s=0,
                      default_toleration_seconds=None)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(dynamic_policy())
    cp.apply(deployment(6))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    before = {t.name: t.replicas for t in rb.spec.clusters}
    assert sum(before.values()) == 6 and len(before) == 2

    # m2 dies: status controller marks NotReady, taints, taint manager evicts
    cp.member("m2").healthy = False
    cp.tick()
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    after = {t.name: t.replicas for t in rb.spec.clusters}
    assert "m2" not in after
    assert sum(after.values()) == 6  # lost replicas re-placed on m1
    # eviction task drained (grace period 0) -> stale Work removed
    assert not rb.spec.graceful_eviction_tasks
    from karmada_tpu.controllers.binding import work_name

    assert cp.store.try_get(Work.KIND, "karmada-es-m2", work_name(rb)) is None


def test_eviction_task_keeps_stale_work_until_drained():
    cp = ControlPlane(eviction_grace_period_s=3600)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(dynamic_policy())
    cp.apply(deployment(6))
    cp.tick()

    cp.member("m2").healthy = False
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    if rb.spec.graceful_eviction_tasks:
        from karmada_tpu.controllers.binding import work_name

        # replacement not yet healthy: old Work must survive the transition
        assert cp.store.try_get(Work.KIND, "karmada-es-m2",
                                work_name(rb)) is not None
    # after replacement turns healthy the task drains
    cp.tick()
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert not rb.spec.graceful_eviction_tasks


def test_cluster_recovery_removes_taint():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    cp.member("m1").healthy = False
    cp.tick()
    cluster = cp.store.get("Cluster", "", "m1")
    assert any(t.key == "cluster.karmada.io/not-ready" for t in cluster.spec.taints)
    cp.member("m1").healthy = True
    cp.tick()
    cluster = cp.store.get("Cluster", "", "m1")
    assert not cluster.spec.taints


def test_application_failover_moves_unhealthy_workload():
    cp = ControlPlane(eviction_grace_period_s=0)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(dynamic_policy(
        failover=FailoverBehavior(toleration_seconds=0)))
    cp.apply(deployment(4))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    targets = {t.name for t in rb.spec.clusters}
    assert len(targets) == 2

    # squeeze m1 so its replicas cannot be admitted -> Unhealthy there
    victim = sorted(targets)[0]
    cp.member(victim).cpu_allocatable_milli = 100
    cp.tick()
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    after = {t.name: t.replicas for t in rb.spec.clusters}
    assert victim not in after
    assert sum(after.values()) == 4


def test_descheduler_moves_stuck_replicas():
    cp = ControlPlane(enable_descheduler=True)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(dynamic_policy())
    cp.apply(deployment(8))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    split = {t.name: t.replicas for t in rb.spec.clusters}
    assert sum(split.values()) == 8

    # m2 loses capacity after placement: its replicas get stuck
    victim = sorted(split)[1]
    other = sorted(split)[0]
    cp.member(victim).cpu_allocatable_milli = 1000  # fits only 2 of 500m
    cp.tick()
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    after = {t.name: t.replicas for t in rb.spec.clusters}
    assert sum(after.values()) == 8
    assert after.get(victim, 0) <= 2
    assert after[other] >= 6


def test_namespace_sync_to_all_members():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    cp.apply({"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "team-a"}})
    cp.tick()
    assert cp.member("m1").get("Namespace", "", "team-a") is not None
    # late-joining member receives existing namespaces
    cp.add_member("m2")
    cp.tick()
    assert cp.member("m2").get("Namespace", "", "team-a") is not None


def test_dependencies_follow_parent_schedule():
    cp = ControlPlane()
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply({"apiVersion": "v1", "kind": "ConfigMap",
              "metadata": {"name": "app-config", "namespace": "default"},
              "data": {"k": "v"}})
    cp.apply_policy(dynamic_policy(propagate_deps=True))
    cp.apply(deployment(4, volumes=[
        {"name": "cfg", "configMap": {"name": "app-config"}}]))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    attached = cp.store.get(ResourceBinding.KIND, "default", "app-config-configmap")
    assert attached.spec.required_by[0].clusters == rb.spec.clusters
    for t in rb.spec.clusters:
        assert cp.member(t.name).get("ConfigMap", "default", "app-config") is not None


def test_toleration_seconds_delays_and_cancels_eviction():
    """Defaulted 300s not-ready tolerations (webhook
    --default-not-ready-toleration-seconds): a taint evicts only after the
    toleration expires, and a taint cleared before the deadline cancels
    the pending eviction — a brief flap never evicts (taint_manager.go
    tolerationTime semantics)."""
    import time as _time

    clock = {"now": 1000.0}
    cp = ControlPlane(clock=lambda: clock["now"])
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.apply_policy(dynamic_policy())
    cp.apply(deployment(replicas=4))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    # the defaulting webhook injected the 300s tolerations
    tols = {t.key: t.toleration_seconds
            for t in rb.spec.placement.cluster_tolerations}
    assert tols.get("cluster.karmada.io/not-ready") == 300

    # flap: cluster goes unhealthy (taint added), recovers quickly
    cp.member("m1").healthy = False
    cp.tick()
    from karmada_tpu.models.cluster import Cluster

    cluster = cp.store.get(Cluster.KIND, "", "m1")
    assert any(t.key.endswith("not-ready") for t in cluster.spec.taints)
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert not rb.spec.graceful_eviction_tasks  # tolerated: no eviction yet
    clock["now"] += 60.0
    cp.member("m1").healthy = True
    cp.tick()
    clock["now"] += 600.0  # well past where the deadline would have been
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert not rb.spec.graceful_eviction_tasks  # cancelled by recovery

    # sustained failure: eviction fires once the toleration expires, and
    # the replicas land on the healthy survivor (the graceful task drains
    # in the same round because the replacement is immediately healthy)
    cp.member("m2").healthy = False
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert any(t.name == "m2" for t in rb.spec.clusters)  # still tolerated
    clock["now"] += 301.0
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert not any(t.name == "m2" for t in rb.spec.clusters)
    assert sum(t.replicas for t in rb.spec.clusters) == 4


def test_stateful_failover_injection_propagates_preserved_labels():
    """StatefulFailoverInjection (gated): on application failover the
    evicted cluster's collected status fields are preserved on the eviction
    task and re-injected as labels into the replacement cluster's rendered
    Work (reference applicationfailover/common.go:139-170 buildTaskOptions
    + binding/common.go:171-207 injectReservedLabelState).  Surgical
    controller-level flow: the payload only lives while the eviction task
    does, so each hop is asserted mid-flight."""
    from karmada_tpu.controllers.binding import BindingController, work_name
    from karmada_tpu.controllers.failover import ApplicationFailoverController
    from karmada_tpu.models.cluster import Cluster
    from karmada_tpu.models.policy import StatePreservationRule
    from karmada_tpu.models.unstructured import Unstructured
    from karmada_tpu.models.work import (
        AggregatedStatusItem,
        ObjectReference,
        ResourceBindingSpec,
        TargetCluster,
    )
    from karmada_tpu.store.store import ObjectStore
    from karmada_tpu.store.worker import Runtime
    from karmada_tpu.utils.features import GATES

    GATES.set("StatefulFailoverInjection", True)
    try:
        store = ObjectStore()
        runtime = Runtime()
        clock = [1000.0]
        afc = ApplicationFailoverController(store, runtime,
                                            clock=lambda: clock[0])
        BindingController(store, runtime)
        for m in ("m1", "m2"):
            store.create(Cluster(metadata=ObjectMeta(name=m)))
        store.create(Unstructured.from_manifest(deployment(4)))
        rb = ResourceBinding(
            metadata=ObjectMeta(name="app-deployment", namespace="default"),
            spec=ResourceBindingSpec(
                resource=ObjectReference(api_version="apps/v1",
                                         kind="Deployment",
                                         namespace="default", name="app",
                                         uid="u1"),
                replicas=4,
                clusters=[TargetCluster(name="m1", replicas=4)],
                failover=FailoverBehavior(
                    toleration_seconds=0, purge_mode="Immediately",
                    state_preservation=[
                        StatePreservationRule(
                            "failover.karmada.io/observed-replicas",
                            "{.replicas}"),
                        StatePreservationRule(
                            "failover.karmada.io/ready", ".readyReplicas"),
                    ]),
            ),
        )
        rb.status.aggregated_status = [AggregatedStatusItem(
            cluster_name="m1",
            status={"replicas": 4, "readyReplicas": 0},
            applied=True, health="Unhealthy",
        )]
        store.create(rb)
        runtime.pump()

        # two periodic rounds (eviction needs a prior unhealthy sighting)
        afc.run_once()
        clock[0] += 1.0
        afc.run_once()
        rb = store.get(ResourceBinding.KIND, "default", "app-deployment")
        assert not rb.spec.clusters  # m1 evicted
        task = rb.spec.graceful_eviction_tasks[-1]
        assert task.purge_mode == "Immediately"
        assert task.clusters_before_failover == ["m1"]
        assert task.preserved_label_state[
            "failover.karmada.io/observed-replicas"] == "4"
        assert task.preserved_label_state[
            "failover.karmada.io/ready"] == "0"

        # scheduler re-places onto m2 (single target) -> render injects
        def reschedule(obj):
            obj.spec.clusters = [TargetCluster(name="m2", replicas=4)]
        store.mutate(ResourceBinding.KIND, "default", "app-deployment",
                     reschedule)
        runtime.pump()
        rb = store.get(ResourceBinding.KIND, "default", "app-deployment")
        w = store.get(Work.KIND, "karmada-es-m2", work_name(rb))
        labels = w.spec.workload[0]["metadata"].get("labels", {})
        assert labels.get("failover.karmada.io/observed-replicas") == "4"
        assert labels.get("failover.karmada.io/ready") == "0"
        # Immediately purge: the old cluster's Work is NOT kept alive
        assert store.try_get(Work.KIND, "karmada-es-m1",
                             work_name(rb)) is None
        # the template itself is NOT mutated -- injection is render-scoped
        tmpl = store.get("Deployment", "default", "app")
        assert "failover.karmada.io/observed-replicas" not in (
            tmpl.manifest["metadata"].get("labels") or {})
    finally:
        GATES.set("StatefulFailoverInjection", False)


def test_stateful_failover_injection_gate_off_by_default():
    """With the gate off (default) the eviction path records no preserved
    payload and rendering injects nothing."""
    from karmada_tpu.controllers.failover import (
        build_preserved_label_state,
        parse_json_path,
    )
    from karmada_tpu.models.policy import StatePreservationRule

    # jsonpath evaluator unit checks (helper/failover.go parseJSONValue)
    st = {"replicas": 3, "conds": [{"type": "Ready", "ok": True}],
          "name": "db-0"}
    assert parse_json_path(st, "{.replicas}") == "3"
    assert parse_json_path(st, ".conds[0].type") == "Ready"
    assert parse_json_path(st, "conds[0].ok") == "true"
    assert parse_json_path(st, "{.name}") == "db-0"
    import pytest as _pytest

    with _pytest.raises(KeyError):
        parse_json_path(st, "{.missing}")
    with _pytest.raises(KeyError):
        parse_json_path(st, ".conds[7].type")
    assert build_preserved_label_state(
        [StatePreservationRule("a", "{.replicas}")], st) == {"a": "3"}

    cp = ControlPlane(eviction_grace_period_s=600)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(dynamic_policy(failover=FailoverBehavior(
        toleration_seconds=0,
        state_preservation=[StatePreservationRule("x", "{.replicas}")])))
    cp.apply(deployment(4))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    victim = sorted(t.name for t in rb.spec.clusters)[0]
    cp.member(victim).cpu_allocatable_milli = 100
    cp.tick()
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert victim not in {t.name for t in rb.spec.clusters}
    for task in rb.spec.graceful_eviction_tasks:
        assert task.preserved_label_state == {}
