"""karmadactl CLI over a persisted control plane directory.

Reference: pkg/karmadactl/ (init/join/unjoin/get/apply/promote/cordon/
top/interpret subcommands).
"""

import json

import pytest

from karmada_tpu.cli import main

CONFTEST_ENV_NOTE = "runs on the CPU platform via tests/conftest.py"


def run(tmp_path, *argv, capsys=None):
    rc = main(["--dir", str(tmp_path / "plane"), *argv])
    return rc


def deployment_yaml(tmp_path, replicas=4):
    p = tmp_path / "deploy.yaml"
    p.write_text(f"""
apiVersion: apps/v1
kind: Deployment
metadata:
  name: web
  namespace: default
spec:
  replicas: {replicas}
  template:
    spec:
      containers:
        - name: c
          resources:
            requests:
              cpu: 100m
              memory: 1Gi
""")
    return str(p)


def policy_yaml(tmp_path):
    # policies are typed objects; drive through apply of the template plus a
    # store-side policy via the python API is the normal path — the CLI
    # covers templates, so tests create the policy directly
    return None


def test_init_join_get_roundtrip(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1", "--cpu", "64") == 0
    assert run(tmp_path, "join", "m2", "--cpu", "32", "--region", "eu") == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Cluster") == 0
    out = capsys.readouterr().out
    assert "m1" in out and "m2" in out
    # state survives across invocations (each call is a fresh process-load)
    assert run(tmp_path, "unjoin", "m2") == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Cluster") == 0
    out = capsys.readouterr().out
    assert "m2" not in out


def test_apply_schedules_through_real_pipeline(tmp_path, capsys):
    run(tmp_path, "init")
    run(tmp_path, "join", "m1")
    run(tmp_path, "join", "m2")
    # policy via the python API against the same persisted plane
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.policy import (
        REPLICA_SCHEDULING_DUPLICATED,
        ObjectMeta,
        Placement,
        PropagationPolicy,
        PropagationSpec,
        ReplicaSchedulingStrategy,
        ResourceSelector,
    )

    cp = _load_plane(str(tmp_path / "plane"))
    cp.store.create(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        ),
    ))
    cp.tick()
    cp.checkpoint()

    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "ResourceBinding", "-n", "default") == 0
    assert "web-deployment" in capsys.readouterr().out
    # proxy read: the workload landed in the member
    assert run(tmp_path, "get", "Deployment", "--cluster", "m1",
               "-n", "default", "-o", "json") == 0
    got = json.loads(capsys.readouterr().out.splitlines()[0])
    assert got["metadata"]["name"] == "web"


def test_cordon_taints_cluster(tmp_path, capsys):
    run(tmp_path, "init")
    run(tmp_path, "join", "m1")
    assert run(tmp_path, "cordon", "m1") == 0
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.cluster import Cluster

    cp = _load_plane(str(tmp_path / "plane"))
    taints = cp.store.get(Cluster.KIND, "", "m1").spec.taints
    assert any(t.key == "cluster.karmada.io/cordoned" for t in taints)
    assert run(tmp_path, "uncordon", "m1") == 0
    cp = _load_plane(str(tmp_path / "plane"))
    assert not cp.store.get(Cluster.KIND, "", "m1").spec.taints


def test_top_clusters(tmp_path, capsys):
    run(tmp_path, "init")
    run(tmp_path, "join", "m1", "--cpu", "8")
    capsys.readouterr()
    assert run(tmp_path, "top", "clusters") == 0
    out = capsys.readouterr().out
    assert "m1" in out and "8000m" in out


def test_interpret_dry_run(tmp_path, capsys):
    f = deployment_yaml(tmp_path, replicas=7)
    assert main(["--dir", str(tmp_path / "plane"), "interpret", "-f", f]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["replicas"] == 7
    assert got["requirements"]["cpu"] == "100m"


def test_interpret_with_customization(tmp_path, capsys):
    m = tmp_path / "widget.yaml"
    m.write_text("""
apiVersion: example.io/v1
kind: Widget
metadata: {name: w, namespace: default}
spec: {size: 9}
""")
    c = tmp_path / "cust.yaml"
    c.write_text("""
customizations:
  InterpretReplica: "get(obj, 'spec.size', 0)"
""")
    assert main(["--dir", str(tmp_path / "plane"), "interpret", "-f", str(m),
                 "--customization", str(c)]) == 0
    assert json.loads(capsys.readouterr().out)["replicas"] == 9


def test_version(tmp_path, capsys):
    assert run(tmp_path, "version") == 0
    assert "karmada-tpu" in capsys.readouterr().out


def test_label_annotate_taint_describe_delete(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1") == 0
    assert run(tmp_path, "label", "Cluster", "m1", "tier=gold", "env=prod") == 0
    assert run(tmp_path, "annotate", "Cluster", "m1", "note=hello") == 0
    assert run(tmp_path, "label", "Cluster", "m1", "env-") == 0
    capsys.readouterr()
    assert run(tmp_path, "describe", "Cluster", "m1") == 0
    desc = capsys.readouterr().out
    assert "tier" in desc and "gold" in desc and "env" not in json.loads(
        desc.split("\nEvents:")[0])["metadata"]["labels"]
    assert run(tmp_path, "taint", "m1", "maint=true:NoSchedule") == 0
    capsys.readouterr()
    assert run(tmp_path, "describe", "Cluster", "m1") == 0
    assert "maint" in capsys.readouterr().out
    assert run(tmp_path, "taint", "m1", "maint-") == 0
    # delete an applied template
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    assert run(tmp_path, "delete", "Deployment", "web", "-n", "default") == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Deployment", "-n", "default") == 0
    assert "web" not in capsys.readouterr().out


def test_api_resources_and_explain(tmp_path, capsys):
    assert run(tmp_path, "api-resources") == 0
    out = capsys.readouterr().out
    assert "PropagationPolicy" in out and "ResourceBinding" in out
    assert run(tmp_path, "explain", "PropagationPolicy") == 0
    out = capsys.readouterr().out
    assert "resource_selectors" in out
    assert run(tmp_path, "explain", "NoSuchKind") == 1


def test_token_register_unregister_pull_mode(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "token", "create") == 0
    token = capsys.readouterr().out.strip().splitlines()[-1]
    assert run(tmp_path, "register", "edge-1", "--token", "nope") == 1
    capsys.readouterr()
    assert run(tmp_path, "register", "edge-1", "--token", token) == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Cluster") == 0
    assert "edge-1" in capsys.readouterr().out
    assert run(tmp_path, "unregister", "edge-1") == 0


def test_addons_and_deinit(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "addons", "enable", "multicluster-service") == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "ConfigMap", "-n", "karmada-system",
               "-o", "json") == 0
    data = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    gates = [d for d in data if d["metadata"]["name"] == "feature-gates"]
    assert gates and gates[0]["data"]["MultiClusterService"] is True
    assert run(tmp_path, "deinit") == 1  # refuses without --force
    assert run(tmp_path, "deinit", "--force") == 0
    assert not (tmp_path / "plane").exists()


def test_addons_gate_rehydrates_across_invocations(tmp_path):
    from karmada_tpu.cli import _load_plane

    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "addons", "enable", "multicluster-service") == 0
    cp = _load_plane(str(tmp_path / "plane"))
    assert cp.gates.enabled("MultiClusterService") is True
    assert run(tmp_path, "addons", "disable", "multicluster-service") == 0
    cp = _load_plane(str(tmp_path / "plane"))
    assert cp.gates.enabled("MultiClusterService") is False


def test_completion_and_options(tmp_path, capsys):
    assert run(tmp_path, "completion") == 0
    out = capsys.readouterr().out
    assert "complete -F" in out and "describe" in out and "serve" in out
    assert run(tmp_path, "options") == 0
    assert "--dir" in capsys.readouterr().out


def test_patch_template_scales_replicas(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1") == 0
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path, replicas=4)) == 0
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", '{"spec": {"replicas": 7}}') == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Deployment", "web", "-n", "default",
               "-o", "json") == 0
    doc = json.loads(capsys.readouterr().out.splitlines()[0])
    assert doc["spec"]["replicas"] == 7
    # bad patch and typed-object refusal
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", "not-json") == 1
    assert run(tmp_path, "patch", "Cluster", "m1", "-p", '{"spec": {}}') == 1


def test_patch_metadata_labels_and_null_semantics(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    # label patch must survive to_manifest's ObjectMeta re-sync
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", '{"metadata": {"labels": {"app": "api"}}}') == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Deployment", "web", "-n", "default",
               "-o", "json") == 0
    doc = json.loads(capsys.readouterr().out.splitlines()[0])
    assert doc["metadata"]["labels"]["app"] == "api"
    # nulls are stripped even inside a freshly-created subtree (RFC 7386)
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", '{"spec": {"fresh": {"a": 1, "b": null}}}') == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Deployment", "web", "-n", "default",
               "-o", "json") == 0
    doc = json.loads(capsys.readouterr().out.splitlines()[0])
    assert doc["spec"]["fresh"] == {"a": 1}
    # identity fields refuse
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", '{"metadata": {"name": "x"}}') == 1
    assert run(tmp_path, "patch", "Deployment", "web", "-n", "default",
               "-p", '{"kind": "Job"}') == 1


def test_kind_aware_printers(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1", "--region", "us") == 0
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    capsys.readouterr()
    assert run(tmp_path, "get", "Cluster") == 0
    out = capsys.readouterr().out
    assert "MODE" in out and "REGION" in out and "us" in out
    assert run(tmp_path, "get", "Deployment", "-n", "default") == 0
    out = capsys.readouterr().out
    assert "KIND" in out and "REPLICAS" in out


def test_work_printer_columns(tmp_path, capsys):
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.policy import (
        PropagationPolicy, PropagationSpec, Placement, ResourceSelector)
    from karmada_tpu.models.meta import ObjectMeta

    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1") == 0
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    cp = _load_plane(str(tmp_path / "plane"))
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name="pp"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name="web")],
            placement=Placement())))
    cp.tick()
    cp.checkpoint()
    capsys.readouterr()
    assert run(tmp_path, "get", "Work") == 0
    out = capsys.readouterr().out
    assert "MANIFESTS" in out and "APPLIED" in out


def test_top_pods(tmp_path, capsys):
    assert run(tmp_path, "init") == 0
    assert run(tmp_path, "join", "m1") == 0
    assert run(tmp_path, "apply", "-f", deployment_yaml(tmp_path)) == 0
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement, PropagationPolicy, PropagationSpec, ResourceSelector)

    cp = _load_plane(str(tmp_path / "plane"))
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name="pp"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name="web")],
            placement=Placement())))
    cp.tick(); cp.checkpoint()
    capsys.readouterr()
    assert run(tmp_path, "top", "pods", "web", "-n", "default") == 0
    out = capsys.readouterr().out
    assert "CLUSTER" in out and "m1" in out and "CPU" in out


def test_create_refuses_overwrite(tmp_path, capsys):
    run(tmp_path, "init")
    assert run(tmp_path, "create", "-f", deployment_yaml(tmp_path)) == 0
    assert "created" in capsys.readouterr().out
    assert run(tmp_path, "create", "-f", deployment_yaml(tmp_path)) == 1
    assert "already exists" in capsys.readouterr().err


def unserved_work_yaml(tmp_path):
    p = tmp_path / "work.yaml"
    p.write_text("""
apiVersion: work.karmada.io/v9
kind: Work
metadata:
  name: w1
  namespace: default
spec: {}
""")
    return str(p)


def test_apply_unserved_api_version_exits_cleanly(tmp_path, capsys):
    """A registered kind at an unserved apiVersion raises ValueError in
    the codec; apply/create must land it as stderr + exit 1 (the CLI
    convention), never a raw traceback."""
    run(tmp_path, "init")
    capsys.readouterr()
    assert run(tmp_path, "apply", "-f", unserved_work_yaml(tmp_path)) == 1
    err = capsys.readouterr().err
    assert "not served at apiVersion" in err
    assert run(tmp_path, "create", "-f", unserved_work_yaml(tmp_path)) == 1
    assert "not served at apiVersion" in capsys.readouterr().err


def test_edit_template_with_editor(tmp_path, capsys, monkeypatch):
    run(tmp_path, "init")
    run(tmp_path, "apply", "-f", deployment_yaml(tmp_path))
    bump = tmp_path / "bump.py"
    bump.write_text(
        "import json, sys\n"
        "p = sys.argv[1]\n"
        "d = json.load(open(p))\n"
        "d['spec']['replicas'] = 7\n"
        "json.dump(d, open(p, 'w'))\n"
    )
    monkeypatch.setenv("EDITOR", f"python3 {bump}")
    capsys.readouterr()
    assert run(tmp_path, "edit", "Deployment", "web", "-n", "default") == 0
    assert run(tmp_path, "get", "Deployment", "web", "-n", "default",
               "-o", "json") == 0
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.startswith("{")]
    assert json.loads(out[-1])["spec"]["replicas"] == 7


def test_edit_rejects_identity_change(tmp_path, capsys, monkeypatch):
    run(tmp_path, "init")
    run(tmp_path, "apply", "-f", deployment_yaml(tmp_path))
    rename = tmp_path / "rename.py"
    rename.write_text(
        "import json, sys\n"
        "p = sys.argv[1]\n"
        "d = json.load(open(p))\n"
        "d['metadata']['name'] = 'other'\n"
        "json.dump(d, open(p, 'w'))\n"
    )
    monkeypatch.setenv("EDITOR", f"python3 {rename}")
    assert run(tmp_path, "edit", "Deployment", "web", "-n", "default") == 1
    assert "cannot change" in capsys.readouterr().err


def _propagate_web(tmp_path):
    """init + join m1 + duplicate-propagate the web deployment, ticked to
    ready so the member synthesizes pods."""
    run(tmp_path, "init")
    run(tmp_path, "join", "m1")
    run(tmp_path, "apply", "-f", deployment_yaml(tmp_path))
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement, PropagationPolicy, PropagationSpec, ResourceSelector)

    cp = _load_plane(str(tmp_path / "plane"))
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name="pp"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name="web")],
            placement=Placement())))
    cp.tick()
    cp.checkpoint()


def test_logs_through_cluster_proxy(tmp_path, capsys):
    _propagate_web(tmp_path)
    capsys.readouterr()
    assert run(tmp_path, "logs", "web-0", "--cluster", "m1",
               "-n", "default") == 0
    out = capsys.readouterr().out
    assert "web-0 started on m1" in out
    assert "created" in out
    # the lifecycle journal recorded the readiness transition
    assert "readyReplicas" in out
    # --tail bounds the stream
    assert run(tmp_path, "logs", "web-0", "--cluster", "m1",
               "-n", "default", "--tail", "1") == 0
    assert len(capsys.readouterr().out.splitlines()) == 1
    # unknown pod is an error, not an empty stream
    assert run(tmp_path, "logs", "nope-0", "--cluster", "m1",
               "-n", "default") == 1


def test_exec_and_attach_through_cluster_proxy(tmp_path, capsys):
    _propagate_web(tmp_path)
    capsys.readouterr()
    assert run(tmp_path, "exec", "web-0", "--cluster", "m1",
               "-n", "default", "--", "hostname") == 0
    assert capsys.readouterr().out.strip() == "web-0"
    assert run(tmp_path, "exec", "web-0", "--cluster", "m1",
               "-n", "default", "--", "env") == 0
    out = capsys.readouterr().out
    assert "KARMADA_CLUSTER=m1" in out and "WORKLOAD=Deployment/web" in out
    assert run(tmp_path, "attach", "web-0", "--cluster", "m1",
               "-n", "default") == 0
    assert "attached to web-0 in m1" in capsys.readouterr().out


def test_get_pods_through_cluster_proxy(tmp_path, capsys):
    _propagate_web(tmp_path)
    capsys.readouterr()
    assert run(tmp_path, "get", "Pod", "--cluster", "m1") == 0
    out = capsys.readouterr().out
    assert "web-0" in out and "Deployment/web" in out and "OWNER" in out
    assert run(tmp_path, "get", "Pod", "web-1", "--cluster", "m1",
               "-o", "json") == 0
    got = json.loads(capsys.readouterr().out.strip())
    assert got == {"name": "web-1", "namespace": "default",
                   "owner": "Deployment/web", "ready": True}


def test_logs_tail_zero_is_empty(tmp_path, capsys):
    _propagate_web(tmp_path)
    capsys.readouterr()
    assert run(tmp_path, "logs", "web-0", "--cluster", "m1",
               "-n", "default", "--tail", "0") == 0
    assert capsys.readouterr().out == ""


def test_get_named_standalone_pod_shows_manifest(tmp_path, capsys):
    _propagate_web(tmp_path)
    from karmada_tpu.cli import _load_plane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement, PropagationPolicy, PropagationSpec, ResourceSelector)

    # propagate a standalone Pod so the member rehydrates it from Works
    cp = _load_plane(str(tmp_path / "plane"))
    cp.apply({"apiVersion": "v1", "kind": "Pod",
              "metadata": {"namespace": "default", "name": "solo"},
              "spec": {"containers": [{"name": "c"}]}})
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name="pp-pod"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="v1", kind="Pod", name="solo")],
            placement=Placement())))
    cp.tick()
    cp.checkpoint()
    capsys.readouterr()
    # a real Pod object answers with its full manifest, not the summary
    assert run(tmp_path, "get", "Pod", "solo", "--cluster", "m1",
               "-n", "default", "-o", "json") == 0
    got = json.loads(capsys.readouterr().out.strip())
    assert got["spec"] == {"containers": [{"name": "c"}]}
    # tail larger than the stream returns everything (kubectl semantics)
    assert run(tmp_path, "logs", "web-0", "--cluster", "m1",
               "-n", "default", "--tail", "999") == 0
    assert "web-0 started on m1" in capsys.readouterr().out
    # not-found errors print clean text, no KeyError repr quotes
    assert run(tmp_path, "logs", "nope-0", "--cluster", "m1",
               "-n", "default") == 1
    err = capsys.readouterr().err
    assert err.startswith("pod default/nope-0 not found")


def test_get_pods_lowercase_alias(tmp_path, capsys):
    _propagate_web(tmp_path)
    capsys.readouterr()
    assert run(tmp_path, "get", "pods", "--cluster", "m1") == 0
    assert "web-0" in capsys.readouterr().out


def test_top_nodes(tmp_path, capsys):
    run(tmp_path, "init")
    run(tmp_path, "join", "m1", "--cpu", "32")
    run(tmp_path, "join", "m2", "--cpu", "64")
    assert run(tmp_path, "top", "nodes") == 0
    out = capsys.readouterr().out
    assert "m1-node-0" in out and "m2-node-0" in out and "CPU%" in out
