"""The driver's hooks must keep working between rounds: entry() compiles
and runs single-device; dryrun_multichip shards the full step over a
(bindings, clusters) mesh (conftest already pins the 8-device virtual CPU
platform, which force_cpu detects and reuses)."""

from __future__ import annotations

import jax

import __graft_entry__ as graft


def test_entry_runs():
    fn, args = graft.entry()
    out = fn(*args)
    jax.block_until_ready(out)
    rep = out[0]
    assert rep.ndim == 2


def test_dryrun_multichip_two_devices():
    graft.dryrun_multichip(2)


def test_dryrun_multichip_eight_devices():
    graft.dryrun_multichip(8)
