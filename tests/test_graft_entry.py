"""The driver's hooks must keep working between rounds: entry() compiles
and runs single-device; dryrun_multichip shards the full step over a
(bindings, clusters) mesh (conftest already pins the 8-device virtual CPU
platform, which force_cpu detects and reuses).

The fast tests skip the production-shape parity pass (4096x5000 takes
minutes on the virtual CPU mesh); KARMADA_TPU_FULL_DRYRUN=1 runs it the
way the driver does."""

from __future__ import annotations

import os

import jax
import pytest

import __graft_entry__ as graft


def test_entry_runs():
    fn, args = graft.entry()
    out = fn(*args)
    jax.block_until_ready(out)
    rep = out[0]
    assert rep.ndim == 2


def test_dryrun_multichip_two_devices():
    graft.dryrun_multichip(2, production_shape=False)


def test_dryrun_multichip_eight_devices():
    graft.dryrun_multichip(8, production_shape=False)


@pytest.mark.skipif(
    os.environ.get("KARMADA_TPU_FULL_DRYRUN") != "1",
    reason="production-shape parity is opt-in: set KARMADA_TPU_FULL_DRYRUN=1 "
           "(~10 min at the default 256x1256 scaled shape; "
           "KARMADA_TPU_PARITY_SHAPE=4096x5000 for the full bench chunk — "
           "hours on a single-core virtual mesh)",
)
def test_dryrun_multichip_production_shape_parity():
    graft.dryrun_multichip(8)
