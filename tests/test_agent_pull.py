"""Pull-mode agent + certificate bootstrap/rotation.

Reference: cmd/agent/app/agent.go:140-145 (the agent runs clusterStatus,
execution, workStatus controllers inside the member cluster),
pkg/controllers/certificate/agent_csr_approving.go:59 and
cert_rotation_controller.go:89.
"""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.certs import (
    AGENT_USER_PREFIX,
    CertificateSigningRequest,
    ClusterCredential,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_DIVISION_WEIGHTED,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    ClusterPreferences,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def nginx(replicas=4):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "nginx", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    )


def mixed_plane(clock=None):
    cp = ControlPlane(backend="serial", clock=clock)
    cp.add_member("push-1", cpu_milli=64_000)
    cp.add_member("pull-1", cpu_milli=64_000, sync_mode="Pull")
    cp.tick()
    return cp


def test_pull_member_gets_workload_via_agent():
    cp = mixed_plane()
    cp.store.create(policy())
    cp.apply(nginx())
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert {tc.name for tc in rb.spec.clusters} == {"push-1", "pull-1"}
    # the workload landed in BOTH members — pull via the agent, not the
    # push execution controller (which does not know the pull member)
    assert cp.members["pull-1"].get("Deployment", "default", "nginx") is not None
    assert "pull-1" not in cp.execution.members
    assert "pull-1" in cp.agents


def test_pull_member_status_reflected_by_agent():
    cp = mixed_plane()
    cp.store.create(policy())
    cp.apply(nginx())
    cp.tick()
    cp.members["pull-1"].tick()  # member workload turns ready
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    agg = {a.cluster_name: a.status for a in rb.status.aggregated_status}
    assert "pull-1" in agg and agg["pull-1"].get("readyReplicas", 0) > 0
    # cluster status heartbeat comes from the agent too
    cluster = cp.store.get(Cluster.KIND, "", "pull-1")
    assert cluster.status.resource_summary is not None
    assert cluster.ready


def test_agent_bootstrap_csr_approved_and_credential_issued():
    cp = mixed_plane()
    csr = cp.store.get(CertificateSigningRequest.KIND, "", "bootstrap-pull-1")
    assert csr.status.approved
    assert csr.status.expires_at is not None
    cred = cp.store.get(ClusterCredential.KIND, "", "pull-1")
    assert cred.status.expires_at == csr.status.expires_at


def test_csr_with_wrong_identity_denied():
    cp = mixed_plane()
    bad = CertificateSigningRequest(metadata=ObjectMeta(name="evil"))
    bad.spec.cluster = "pull-1"
    bad.spec.username = "system:karmada:agent:other"
    cp.store.create(bad)
    cp.tick()
    got = cp.store.get(CertificateSigningRequest.KIND, "", "evil")
    assert not got.status.approved
    assert got.status.denied_reason


def test_certificate_rotation_renews_before_expiry():
    clock = FakeClock()
    cp = mixed_plane(clock=clock)
    cred = cp.store.get(ClusterCredential.KIND, "", "pull-1")
    ttl = cred.status.expires_at - cred.status.issued_at
    assert cred.status.rotations == 0
    # inside the threshold window: no rotation yet
    clock.advance(ttl * 0.5)
    cp.tick()
    assert cp.store.get(ClusterCredential.KIND, "", "pull-1").status.rotations == 0
    # past 80% of the lifetime: rotation fires, expiry extends
    clock.advance(ttl * 0.35)
    cp.tick()
    rotated = cp.store.get(ClusterCredential.KIND, "", "pull-1")
    assert rotated.status.rotations >= 1
    assert rotated.status.expires_at > cred.status.expires_at


def test_agent_owns_its_rotation_scope():
    """The agent's rotation loop touches ONLY its own credential
    (cert_rotation_controller.go runs inside the agent binary): another
    pull member's credential is rotated by that member's OWN agent, and
    stopping an agent stops its loop."""
    clock = FakeClock()
    cp = mixed_plane(clock=clock)
    cp.add_member("pull-2", sync_mode="Pull")
    cp.tick()
    cred1 = cp.store.get(ClusterCredential.KIND, "", "pull-1")
    ttl = cred1.status.expires_at - cred1.status.issued_at
    assert cp.agents["pull-1"].cert_rotation.cluster == "pull-1"
    assert cp.agents["pull-2"].cert_rotation.cluster == "pull-2"

    # stop pull-2's agent: its credential must NOT rotate anymore, while
    # pull-1's (live agent) does
    cp.agents["pull-2"].stop()
    clock.advance(ttl * 0.9)
    cp.tick()
    assert cp.store.get(ClusterCredential.KIND, "",
                        "pull-1").status.rotations >= 1
    assert cp.store.get(ClusterCredential.KIND, "",
                        "pull-2").status.rotations == 0
