"""bench.py watchdog: hang detection, fallback, and result preservation.

The device tunnel can stall mid-run (observed: probe ok, then a dispatch
blocked forever on the relay socket).  These tests drive
bench.run_with_watchdog against stub children so no backend is touched.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

STUB = """
import os, sys, time, json
mode = os.environ.get("WD_MODE")
if "--force-cpu" in sys.argv:
    print(json.dumps({"metric": "CPU-FALLBACK (NOT TPU) x", "value": 1,
                      "unit": "b/s", "vs_baseline": 0, "detail": {}}))
    sys.exit(0)
if mode == "result_then_hang":
    print("[bench] working", file=sys.stderr, flush=True)
    print(json.dumps({"metric": "tpu x", "value": 42, "unit": "b/s",
                      "vs_baseline": 9.0, "detail": {"platform": "tpu"}}),
          flush=True)
    time.sleep(1000)   # teardown hang: cpu-idle, silent
elif mode == "clean":
    print(json.dumps({"metric": "tpu x", "value": 7, "unit": "b/s",
                      "vs_baseline": 2.0, "detail": {}}))
elif mode == "silent_hang":
    time.sleep(1000)
"""


@pytest.fixture
def stub_bench(tmp_path, monkeypatch):
    stub = tmp_path / "stub_bench.py"
    stub.write_text(STUB)
    real_abspath = os.path.abspath
    monkeypatch.setattr(
        os.path, "abspath",
        lambda p: str(stub) if str(p).endswith("bench.py") else real_abspath(p),
    )

    def run(mode, timeout=4.0):
        monkeypatch.setenv("WD_MODE", mode)
        return bench.run_with_watchdog([], timeout)
    return run


def test_clean_child_result_passes_through(stub_bench, capfd):
    rc = stub_bench("clean")
    assert rc == 0
    out = capfd.readouterr().out
    assert json.loads(out.splitlines()[-1])["value"] == 7


def test_completed_result_survives_teardown_hang(stub_bench, capfd):
    """A child that prints the result and then hangs in teardown is killed,
    but its real measurement is kept — never replaced by a CPU re-run."""
    rc = stub_bench("result_then_hang")
    assert rc == 0
    d = json.loads(capfd.readouterr().out.splitlines()[-1])
    assert d["value"] == 42 and d["detail"]["platform"] == "tpu"


def test_silent_hang_falls_back_loudly(stub_bench, capfd):
    rc = stub_bench("silent_hang")
    assert rc == 0
    out, err = capfd.readouterr()
    d = json.loads(out.splitlines()[-1])
    assert d["metric"].startswith("CPU-FALLBACK")
    assert "hung" in d["detail"]["tpu_attempt"]
    assert "killing the device attempt" in err


def test_partial_tpu_record_round_trips(tmp_path, capsys):
    """A forward-only on-chip measurement persisted mid-window must be
    reportable by a later (chip-down) bench run, loudly labelled."""
    from types import SimpleNamespace

    args = SimpleNamespace(bindings=100_000, clusters=5_000, chunk=4096,
                           waves=8, carry=False, ckpt_dir=str(tmp_path))
    bench.save_tpu_latest(args.ckpt_dir, args, {
        "metric": "scheduled bindings/sec, ... (forward pass only, "
                  "rebalance pending)",
        "value": 98765.0, "unit": "bindings/s", "vs_baseline": 83.3,
        "detail": {"platform": "tpu", "partial": True},
    })
    rec = bench.load_tpu_latest(args.ckpt_dir, args)
    assert rec is not None
    bench.emit_cached_tpu(rec, why_no_live="probe timed out")
    out = json.loads(capsys.readouterr().out)
    assert out["value"] == 98765.0 and out["vs_baseline"] == 83.3
    assert out["detail"]["cached"] is True and out["detail"]["partial"] is True
    assert "[cached on-TPU measurement]" in out["metric"]

    # a different config must not match the record
    other = SimpleNamespace(**{**vars(args), "clusters": 64})
    assert bench.load_tpu_latest(other.ckpt_dir, other) is None


def test_load_ckpt_skips_legacy_rebalance_records(tmp_path):
    """Cross-version resume: a previous bench version logged the rebalance
    pass as kind="rebalance" (ci=-1) records under the FORWARD sig.
    load_ckpt must skip them — folding them in stored a phantom done[-1]
    and inflated prior_elapsed, deflating resumed throughput."""
    path = str(tmp_path / "chunks.jsonl")
    sig = "b100-c10-k16-w8-cpu-deadbeef"
    recs = [
        {"sig": sig, "session": "s1", "ci": 0, "n": 16, "scheduled": 16,
         "failures": {}, "lat": 0.5, "wall": 0.6, "solve_s": 0.3,
         "t_rel": 1.0},
        # legacy rebalance-pass records under the forward sig
        {"sig": sig, "session": "s1", "kind": "rebalance", "ci": -1,
         "n": 100, "scheduled": 100, "lat": 9.0, "wall": 9.0,
         "t_rel": 500.0},
        {"sig": sig, "session": "s1", "ci": -1, "n": 100, "scheduled": 100,
         "lat": 9.0, "wall": 9.0, "t_rel": 600.0},
        {"sig": sig, "session": "s1", "ci": 1, "n": 16, "scheduled": 15,
         "failures": {"UnschedulableError": 1}, "lat": 0.4, "wall": 0.5,
         "solve_s": 0.2, "t_rel": 2.0},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    done, prior = bench.load_ckpt(path, sig)
    assert set(done) == {0, 1}
    assert prior == 2.0  # the legacy records' t_rel never inflates it


def test_pgroup_cpu_accounting_sees_own_group():
    pg = os.getpgid(0)
    c0 = bench._pgroup_cpu_s(pg)
    x = 0
    for i in range(10**7):
        x += i
    assert bench._pgroup_cpu_s(pg) > c0
