"""Service-name-resolution detector sidecar: probe -> condition -> taint.

Reference: cmd/service-name-resolution-detector-example +
pkg/servicenameresolutiondetector/coredns/detector.go:92, composed with the
ClusterTaintPolicy controller (condition-driven taints).
"""

from __future__ import annotations

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.members.dns_detector import (
    COND_SERVICE_DNS_READY,
    ServiceNameResolutionDetector,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.extras import (
    ClusterTaintPolicy,
    ClusterTaintPolicySpec,
    MatchCondition,
    TaintSpec,
)
from karmada_tpu.models.meta import ObjectMeta, get_condition


def _condition(cp, name):
    c = cp.store.get(Cluster.KIND, "", name)
    return get_condition(c.status.conditions, COND_SERVICE_DNS_READY)


def test_dns_failure_sets_condition_and_policy_taints():
    cp = ControlPlane()
    m1 = cp.add_member("m1")
    cp.add_member("m2")
    det = ServiceNameResolutionDetector(cp.store, m1, cp.runtime, threshold=3)
    cp.tick()
    cond = _condition(cp, "m1")
    assert cond is not None and cond.status == "True"

    # taint policy: condition False -> NoSchedule taint; True -> remove
    cp.store.create(ClusterTaintPolicy(
        metadata=ObjectMeta(name="dns-taint"),
        spec=ClusterTaintPolicySpec(
            add_on_conditions=[MatchCondition(
                condition_type=COND_SERVICE_DNS_READY, operator="In",
                status_values=["False"])],
            remove_on_conditions=[MatchCondition(
                condition_type=COND_SERVICE_DNS_READY, operator="In",
                status_values=["True"])],
            taints=[TaintSpec(key="dns-unavailable", effect="NoSchedule")],
        ),
    ))

    # one flaky probe must NOT flip the condition (windowed vote)
    m1.dns_healthy = False
    det.probe()
    m1.dns_healthy = True
    det.probe()
    det.probe()
    assert _condition(cp, "m1").status == "True"

    # sustained failure flips it and the policy taints the cluster
    m1.dns_healthy = False
    for _ in range(3):
        det.probe()
    cp.tick()
    assert _condition(cp, "m1").status == "False"
    cluster = cp.store.get(Cluster.KIND, "", "m1")
    assert any(t.key == "dns-unavailable" for t in cluster.spec.taints)

    # recovery removes the taint again
    m1.dns_healthy = True
    for _ in range(3):
        det.probe()
    cp.tick()
    assert _condition(cp, "m1").status == "True"
    cluster = cp.store.get(Cluster.KIND, "", "m1")
    assert not any(t.key == "dns-unavailable" for t in cluster.spec.taints)


def test_detector_stop_detaches_from_runtime():
    cp = ControlPlane()
    m1 = cp.add_member("m1")
    det = ServiceNameResolutionDetector(cp.store, m1, cp.runtime, threshold=2)
    det.stop()
    before = len(det._window)
    cp.tick()  # periodics must no longer reach the detector
    assert len(det._window) == before


def test_control_plane_wiring_and_unjoin_teardown():
    cp = ControlPlane()
    cp.add_member("m1")
    det = cp.enable_dns_detector("m1", threshold=2)
    cp.tick()
    assert _condition(cp, "m1") is not None
    cp.unjoin("m1")
    before = len(det._window)
    cp.tick()
    assert len(det._window) == before  # stopped with the member
