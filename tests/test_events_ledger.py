"""Lifecycle ledger (obs/events): coalescing/bounds, timeline ordering,
virtual-clock timestamps, the decision<->event cross-reference, the
admission-gate emitters, gap-free soak timelines + the ledger-derived
conservation verdict, HTTP + karmadactl describe/events smoke, and the
disarmed-overhead / zero-new-compile contracts."""

from __future__ import annotations

import json
import threading
import types
import urllib.error
import urllib.request

import pytest

from karmada_tpu.obs import events as obs_events
from karmada_tpu.utils import events as ev

pytestmark = pytest.mark.events


@pytest.fixture()
def fresh_ledger():
    """A fresh process ledger per test (the global is shared by the
    whole suite); restored to a clean armed default afterwards."""
    led = obs_events.configure(capacity=16384)
    yield led
    obs_events.configure(capacity=16384)


def _ref(name, ns="ns", kind="ResourceBinding"):
    return ev.ObjectRef(kind=kind, namespace=ns, name=name)


# -- coalescing / bounds / eviction ------------------------------------------


def test_tail_coalescing_bumps_count_and_keeps_timeline_gap_free():
    clock = {"t": 0.0}
    led = obs_events.EventLedger(capacity=64, now=lambda: clock["t"])
    r = _ref("a")
    id1 = led.record(r, ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED, "enq")
    clock["t"] = 5.0
    id2 = led.record(r, ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED, "enq")
    assert id1 == id2  # the tail bump returns the coalesced event's id
    clock["t"] = 7.0
    led.record(r, ev.TYPE_NORMAL, ev.REASON_SCHEDULE_BINDING_SUCCEED, "ok")
    # an identical event AFTER an intervening one is a NEW entry —
    # coalescing never reorders history
    clock["t"] = 9.0
    id4 = led.record(r, ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED, "enq")
    assert id4 != id1
    tl = led.timeline("ResourceBinding", "ns", "a")
    assert [e["reason"] for e in tl] == [
        ev.REASON_BINDING_ENQUEUED, ev.REASON_SCHEDULE_BINDING_SUCCEED,
        ev.REASON_BINDING_ENQUEUED]
    assert tl[0]["count"] == 2
    assert tl[0]["first_timestamp"] == 0.0
    assert tl[0]["last_timestamp"] == 5.0
    c = led.counters()
    assert c["recorded"] == 4 and c["coalesced"] == 1 and c["retained"] == 3


def test_capacity_evicts_globally_oldest_and_prunes_timeline_heads():
    led = obs_events.EventLedger(capacity=4)
    for i in range(3):
        led.record(_ref("a"), ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
                   f"m{i}")
    for i in range(3):
        led.record(_ref("b"), ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
                   f"m{i}")
    c = led.counters()
    assert c["retained"] == 4 and c["evicted"] == 2
    # a's timeline lost its HEAD entries, never its tail
    tl_a = led.timeline("ResourceBinding", "ns", "a")
    assert [e["message"] for e in tl_a] == ["m2"]
    assert [e["message"]
            for e in led.timeline("ResourceBinding", "ns", "b")] == \
        ["m0", "m1", "m2"]
    # a fully-pruned object drops out of the index
    for i in range(4):
        led.record(_ref("c"), ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
                   f"x{i}")
    assert led.timeline("ResourceBinding", "ns", "a") == []
    assert led.counters()["objects"] == 1


def test_concurrent_emitters_keep_per_key_order():
    led = obs_events.EventLedger(capacity=100000)
    n_threads, per_thread = 8, 200

    def worker(tid):
        r = _ref(f"k{tid}")
        for i in range(per_thread):
            led.record(r, ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
                       f"step {i}")

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for tid in range(n_threads):
        tl = led.timeline("ResourceBinding", "ns", f"k{tid}")
        # per-key record order survives the interleaving: messages in
        # sequence and ids strictly increasing
        assert [e["message"] for e in tl] == [f"step {i}"
                                              for i in range(per_thread)]
        ids = [e["id"] for e in tl]
        assert ids == sorted(ids)
    assert led.counters()["recorded"] == n_threads * per_thread


def test_virtual_clock_plumbing(fresh_ledger):
    t = {"v": 1_000_000.0}
    prev = obs_events.set_clock(lambda: t["v"])
    try:
        ev.emit_key(("ns", "vc"), ev.TYPE_NORMAL,
                    ev.REASON_BINDING_ENQUEUED, "enq")
        t["v"] = 1_000_500.0
        ev.emit_key(("ns", "vc"), ev.TYPE_NORMAL,
                    ev.REASON_SCHEDULE_BINDING_SUCCEED, "ok")
    finally:
        obs_events.set_clock(prev)
    tl = obs_events.ledger().timeline("ResourceBinding", "ns", "vc")
    assert [e["last_timestamp"] for e in tl] == [1_000_000.0, 1_000_500.0]


def test_disarmed_emitters_record_nothing_and_cost_no_compiles(fresh_ledger):
    from karmada_tpu.ops import solver

    before = obs_events.ledger().counters()["recorded"]
    c_before = solver._jit_cache_size()  # noqa: SLF001
    obs_events.disarm()
    try:
        for i in range(1000):
            assert obs_events.emit_key(
                ("ns", "dis"), ev.TYPE_NORMAL,
                ev.REASON_BINDING_ENQUEUED, "enq") is None
        # the global-view EventRecorder respects the arm state too
        assert ev.EventRecorder().event(
            _ref("dis"), ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
            "enq") is None
    finally:
        obs_events.arm()
    assert obs_events.ledger().counters()["recorded"] == before
    c_after = solver._jit_cache_size()  # noqa: SLF001
    if c_before is not None and c_after is not None:
        assert c_after - c_before == 0
    # a PRIVATE recorder ignores the global arm state (test isolation)
    rec = ev.EventRecorder(capacity=8)
    obs_events.disarm()
    try:
        assert rec.event(_ref("p"), ev.TYPE_NORMAL,
                         ev.REASON_BINDING_ENQUEUED, "enq") is not None
    finally:
        obs_events.arm()


def test_event_recorder_compat_surface():
    """The classic EventRecorder semantics (test_observability's
    contract) hold on a private ledger; a bare recorder shares the
    process ledger."""
    clock = {"t": 0.0}
    rec = ev.EventRecorder(capacity=3, now=lambda: clock["t"])
    r = _ref("a", kind="K")
    rec.event(r, ev.TYPE_WARNING, ev.REASON_SCHEDULE_BINDING_FAILED, "m")
    clock["t"] = 5.0
    rec.event(r, ev.TYPE_WARNING, ev.REASON_SCHEDULE_BINDING_FAILED, "m")
    got = rec.list(kind="K")
    assert len(got) == 1 and got[0].count == 2
    assert got[0].first_timestamp == 0.0 and got[0].last_timestamp == 5.0
    a = ev.EventRecorder()
    b = ev.EventRecorder()
    eid = a.event(_ref("shared"), ev.TYPE_NORMAL,
                  ev.REASON_BINDING_ENQUEUED, "enq")
    assert eid is not None
    assert any(e.ref.name == "shared" for e in b.list(kind="ResourceBinding"))


# -- admission-gate emitters --------------------------------------------------


def test_admission_gate_emits_enqueued_shed_displaced(fresh_ledger):
    from karmada_tpu.scheduler.queue import SchedulingQueue

    q = SchedulingQueue(max_resident=2)
    q.push(("ns", "low1"), priority=0)
    q.push(("ns", "low2"), priority=0)
    assert q.push(("ns", "low3"), priority=0) == "shed"
    assert q.push(("ns", "high"), priority=5) == "admitted"  # displaces
    led = obs_events.ledger()
    assert [e["reason"] for e in
            led.timeline("ResourceBinding", "ns", "low3")] == \
        [ev.REASON_BINDING_SHED]
    tl_low1 = led.timeline("ResourceBinding", "ns", "low1")
    assert [e["reason"] for e in tl_low1] == [
        ev.REASON_BINDING_ENQUEUED, ev.REASON_BINDING_DISPLACED]
    assert [e["reason"] for e in
            led.timeline("ResourceBinding", "ns", "high")] == \
        [ev.REASON_BINDING_ENQUEUED]
    # the scheduler's own result-patch echo pushes stay silent
    q.pop_ready(1)
    q.push(("ns", "echo"), gate_exempt=True)
    assert led.timeline("ResourceBinding", "ns", "echo") == []


# -- scheduler outcomes + the decision cross-reference ------------------------


def _schedule_one_plane(explain=0.0):
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        Placement,
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )

    cp = ControlPlane(backend="serial", explain=explain)
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(),
        ),
    ))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 2, "template": {"spec": {"containers": [
                  {"name": "a",
                   "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()
    return cp


def test_scheduled_event_carries_targets_cycle_and_decision_link(
        fresh_ledger):
    from karmada_tpu.obs import decisions as obs_decisions

    cp = _schedule_one_plane(explain=1.0)
    led = obs_events.ledger()
    tl = led.timeline("ResourceBinding", "default", "app-deployment")
    assert tl, "the binding's lifecycle left no timeline"
    reasons = [e["reason"] for e in tl]
    assert ev.REASON_BINDING_ENQUEUED in reasons
    sched = [e for e in tl
             if e["reason"] == ev.REASON_SCHEDULE_BINDING_SUCCEED]
    assert sched, reasons
    outcome = sched[-1]
    assert "m1(" in outcome["message"]  # targets named in the message
    assert outcome["cycle_id"] is not None and outcome["cycle_id"] >= 1
    # decision <-> event cross-reference (explain armed every cycle)
    rec = obs_decisions.recorder()
    d = rec.get("default/app-deployment")
    assert d is not None
    assert d.get("event_id") == outcome["id"]
    assert outcome["decision_id"] == d.get("id")
    obs_decisions.disable()
    del cp


def test_failed_schedule_event_names_dominant_reason(fresh_ledger):
    from karmada_tpu.models.policy import ClusterAffinity

    cp = _schedule_one_plane()
    cp.store.mutate("PropagationPolicy", "default", "pp", lambda p: setattr(
        p.spec.placement, "cluster_affinity",
        ClusterAffinity(cluster_names=["absent"])))
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "app2", "namespace": "default"},
              "spec": {"replicas": 1, "template": {"spec": {"containers": [
                  {"name": "a"}]}}}})
    cp.tick()
    tl = obs_events.ledger().timeline("ResourceBinding", "default",
                                      "app2-deployment")
    failed = [e for e in tl
              if e["reason"] == ev.REASON_SCHEDULE_BINDING_FAILED]
    assert failed and failed[-1]["type"] == ev.TYPE_WARNING


# -- compressed soak: gap-free timelines + the report's ledger section --------


def _run_soak(scenario_name="steady", seed=0):
    from karmada_tpu.loadgen import (
        LoadDriver,
        ServeSlice,
        ServiceModel,
        VirtualClock,
        get_scenario,
    )

    scenario = get_scenario(scenario_name)
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=seed)
    return plane, driver, driver.run()


@pytest.mark.soak
def test_compressed_soak_timelines_are_gap_free_and_virtual_time(
        fresh_ledger):
    plane, driver, payload = _run_soak("steady")
    led = obs_events.ledger()
    assert payload["injected"] > 0
    for (ns, name) in driver._flight:  # noqa: SLF001 — test owns it
        tl = led.timeline("ResourceBinding", ns, name)
        assert tl, f"{ns}/{name} has no timeline (gap)"
        assert [e["id"] for e in tl] == sorted(e["id"] for e in tl)
        # timestamps live on the VIRTUAL timeline (VirtualClock starts
        # at 1e6), not wall time (~1.7e9) — the recorder-clock satellite
        for e in tl:
            assert 1_000_000.0 <= e["last_timestamp"] < 2_000_000.0, e
    # the SOAK payload's ledger section
    stats = payload["events"]
    assert stats["armed"] and stats["recorded"] > payload["injected"]
    assert stats["events_per_s"] > 0
    assert stats["by_reason"].get(ev.REASON_BINDING_ENQUEUED, 0) >= \
        payload["injected"]
    # the clock was restored on uninstall
    assert led.now is not driver.clock


@pytest.mark.soak
@pytest.mark.chaos
def test_chaos_soak_ledger_conservation_agrees_with_recompute(fresh_ledger):
    """The ISSUE-14 acceptance leg: in a compressed chaos soak, 100% of
    injected bindings have a gap-free timeline whose terminal event
    matches store state, and the ledger-derived conservation verdict
    agrees with the SafetyAuditor's legacy recompute."""
    from karmada_tpu.loadgen import warm_device_path
    from karmada_tpu.loadgen import (
        LoadDriver,
        ServeSlice,
        ServiceModel,
        VirtualClock,
        get_scenario,
    )

    scenario = get_scenario("chaos")
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device",
                       resident=True, resident_audit_interval=0,
                       device_cycle_timeout_s=2.0,
                       device_recover_cycles=2)
    warm_device_path(plane)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=0)
    payload = driver.run()
    audit = payload["safety_audit"]
    assert audit["violations"] == [], json.dumps(audit["violations"],
                                                 indent=2)
    lc = audit["ledger_conservation"]
    assert lc["enabled"] and lc["agrees"], lc
    assert lc["gap_free"] and lc["disagreements"] == 0
    assert lc["checked"] == audit["conservation"]["injected"] > 300
    assert lc["terminal"].get("missing", 0) == 0
    # chaos fault fires made the ledger too
    fires = obs_events.ledger().list(kind="ChaosPlane")
    assert fires and all(
        e.reason == ev.REASON_CHAOS_FAULT_INJECTED for e in fires)


# -- HTTP + CLI smoke ---------------------------------------------------------


def _events_server(fresh=True):
    from karmada_tpu.models.work import ResourceBinding, TargetCluster
    from karmada_tpu.store.store import ObjectStore
    from karmada_tpu.utils.httpserve import ObservabilityServer

    store = ObjectStore()
    rb = ResourceBinding()
    rb.metadata.namespace, rb.metadata.name = "ns", "b1"
    store.create(rb)
    store.mutate("ResourceBinding", "ns", "b1",
                 lambda o: setattr(o.spec, "clusters",
                                   [TargetCluster(name="m1", replicas=2)]))
    ev.emit_key(("ns", "b1"), ev.TYPE_NORMAL, ev.REASON_BINDING_ENQUEUED,
                "enqueued to the active queue (origin=active)")
    ev.emit_key(("ns", "b1"), ev.TYPE_NORMAL,
                ev.REASON_SCHEDULE_BINDING_SUCCEED, "scheduled to m1(2)")
    srv = ObservabilityServer(store=store)
    return srv, srv.start()


def test_debug_events_endpoints(fresh_ledger):
    srv, url = _events_server()
    try:
        p = json.loads(urllib.request.urlopen(url + "/debug/events").read())
        assert p["enabled"] and p["armed"]
        assert p["stats"]["recorded"] >= 2
        assert len(p["recent"]) >= 2
        cursor = max(e["last_seq"] for e in p["recent"])
        # the --watch cursor: only newer ACTIVITY comes back
        p2 = json.loads(urllib.request.urlopen(
            url + f"/debug/events?since={cursor}").read())
        assert p2["recent"] == []
        # a coalesced repeat bumps last_seq, so the watch surfaces it
        # even though no new event id was minted
        ev.emit_key(("ns", "b1"), ev.TYPE_NORMAL,
                    ev.REASON_SCHEDULE_BINDING_SUCCEED,
                    "scheduled to m1(2)")
        p3 = json.loads(urllib.request.urlopen(
            url + f"/debug/events?since={cursor}").read())
        assert [e["count"] for e in p3["recent"]] == [2]
        t = json.loads(urllib.request.urlopen(
            url + "/debug/events/ns/b1").read())
        assert t["count"] == 2
        assert [e["reason"] for e in t["events"]] == [
            ev.REASON_BINDING_ENQUEUED, ev.REASON_SCHEDULE_BINDING_SUCCEED]
        assert t["binding"]["exists"]
        assert t["binding"]["clusters"] == [{"name": "m1", "replicas": 2}]
        # /debug/state carries the ledger counters
        s = json.loads(urllib.request.urlopen(url + "/debug/state").read())
        assert s["events"]["recorded"] >= 2
        # malformed timeline key answers a JSON 404
        try:
            urllib.request.urlopen(url + "/debug/events/nokey")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404 and "error" in json.loads(e.read())
    finally:
        srv.stop()


def test_karmadactl_events_and_describe_render(fresh_ledger, capsys):
    from karmada_tpu import cli

    srv, url = _events_server()
    try:
        rc = cli.cmd_events(types.SimpleNamespace(
            target="", endpoint=url, watch=False, interval=2.0, limit=64))
        assert rc == 0
        out = capsys.readouterr().out
        assert "BindingEnqueued" in out and "ScheduleBindingSucceed" in out
        rc = cli.cmd_events(types.SimpleNamespace(
            target="ns/b1", endpoint=url, watch=False, interval=2.0,
            limit=64))
        assert rc == 0
        out = capsys.readouterr().out
        assert "NAME: ns/b1" in out and "scheduled to m1(2)" in out
        rc = cli.cmd_describe(types.SimpleNamespace(
            kind="ns/b1", name="", namespace="", cluster="",
            endpoint=url, dir=""))
        assert rc == 0
        out = capsys.readouterr().out
        assert "CLUSTERS: m1(2)" in out and "Events (2):" in out
        # a bad target is a usage error, not a traceback
        rc = cli.cmd_events(types.SimpleNamespace(
            target="nokey", endpoint=url, watch=False, interval=2.0,
            limit=64))
        assert rc == 1
    finally:
        srv.stop()


def test_events_parser_wired():
    from karmada_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["events", "ns/b1", "--endpoint", "http://x", "--watch"])
    assert args.command == "events" and args.watch
    args = build_parser().parse_args(
        ["describe", "ns/b1", "--endpoint", "http://x"])
    assert args.command == "describe" and args.endpoint == "http://x"


# -- bench integration --------------------------------------------------------


def test_measure_ledger_overhead_shape(fresh_ledger):
    import bench

    rec = bench.measure_ledger_overhead(reference_cycle_s=0.05, iters=2000)
    assert rec["ledger_armed_per_event_us"] > 0
    assert rec["ledger_disarmed_per_call_us"] > 0
    # disarmed is a global read; armed a dict/deque op — both far under
    # 1% of the 50ms reference cycle
    assert rec["ledger_armed_overhead_pct"] < 1.0
    assert rec["ledger_disarmed_overhead_pct"] < 1.0
    assert rec["ledger_new_compiles"] in (0, None)
    # the measurement must leave the global ledger armed
    assert obs_events.armed()
