"""Admission webhooks + FederatedResourceQuota enforcement gate.

Reference: pkg/webhook/ (karmada-webhook admission for policy CRDs) and
pkg/webhook/resourcebinding/validating.go (FederatedQuotaEnforcement:
deny a schedule-result patch that would exceed the namespace quota, bump
status.overallUsed on success).
"""

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.extras import (
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
)
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_DIVISION_WEIGHTED,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    ClusterPreferences,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
    SpreadConstraint,
)
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.utils.quantity import Quantity
from karmada_tpu.webhook.admission import AdmissionDenied


def nginx(replicas=6, cpu="500m"):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "nginx", "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "template": {"spec": {"containers": [
                {"name": "nginx", "image": "nginx:1.19",
                 "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}},
            ]}},
        },
    }


def policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                    replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                    weight_preference=ClusterPreferences(
                        dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS
                    ),
                )
            ),
        ),
    )


def frq(cpu_milli):
    return FederatedResourceQuota(
        metadata=ObjectMeta(name="quota", namespace="default"),
        spec=FederatedResourceQuotaSpec(
            overall={"cpu": Quantity.from_milli(cpu_milli)}
        ),
    )


def plane(**gates):
    cp = ControlPlane(backend="serial", feature_gates=gates or None)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.tick()
    return cp


def test_policy_validation_rejects_bad_spread():
    cp = plane()
    bad = policy()
    bad.spec.placement.spread_constraints = [
        SpreadConstraint(spread_by_field="cluster", min_groups=3, max_groups=1)
    ]
    with pytest.raises(AdmissionDenied, match="maxGroups lower than minGroups"):
        cp.store.create(bad)


def test_policy_defaulting_fills_preemption():
    cp = plane()
    p = policy()
    p.spec.preemption = ""
    cp.store.create(p)
    assert cp.store.get(PropagationPolicy.KIND, "default", "pp").spec.preemption == "Never"


def test_frq_validation_rejects_negative():
    cp = plane()
    bad = frq(-100)
    with pytest.raises(AdmissionDenied, match="non-negative"):
        cp.store.create(bad)


def test_quota_gate_disabled_by_default():
    cp = plane()
    cp.store.create(frq(1000))  # 1 cpu total; 6 replicas x 500m = 3000m
    cp.store.create(policy())
    cp.apply(nginx())
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    # gate off: scheduling proceeds past the quota
    assert sum(tc.replicas for tc in rb.spec.clusters) == 6


def test_quota_gate_blocks_scheduling():
    cp = plane(FederatedQuotaEnforcement=True)
    cp.store.create(frq(1000))
    cp.store.create(policy())
    cp.apply(nginx())  # needs 3000m > 1000m quota
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert rb.spec.clusters == []
    conds = {c.type: (c.status, c.message) for c in rb.status.conditions}
    assert conds["Scheduled"][0] == "False"
    assert "FederatedResourceQuota" in conds["Scheduled"][1]


def test_quota_gate_allows_within_budget_and_bumps_used():
    cp = plane(FederatedQuotaEnforcement=True)
    cp.store.create(frq(5000))
    cp.store.create(policy())
    cp.apply(nginx())  # 3000m <= 5000m
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert sum(tc.replicas for tc in rb.spec.clusters) == 6
    q = cp.store.get(FederatedResourceQuota.KIND, "default", "quota")
    assert q.status.overall_used["cpu"].milli == 3000


def test_quota_gate_scale_down_releases_budget():
    cp = plane(FederatedQuotaEnforcement=True)
    cp.store.create(frq(3000))
    cp.store.create(policy())
    cp.apply(nginx(replicas=6))  # exactly 3000m
    cp.tick()
    q = cp.store.get(FederatedResourceQuota.KIND, "default", "quota")
    assert q.status.overall_used["cpu"].milli == 3000
    cp.apply(nginx(replicas=2))  # scale down to 1000m
    cp.tick()
    q = cp.store.get(FederatedResourceQuota.KIND, "default", "quota")
    assert q.status.overall_used["cpu"].milli == 1000


def test_interpreter_webhook_admission():
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.config import (
        InterpreterRule,
        ResourceInterpreterWebhook,
        ResourceInterpreterWebhookSpec,
    )
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.webhook.admission import AdmissionDenied

    cp = ControlPlane()

    def mk(endpoint, rules, timeout_s=5.0, name="w"):
        return ResourceInterpreterWebhook(
            metadata=ObjectMeta(name=name),
            spec=ResourceInterpreterWebhookSpec(
                endpoint=endpoint, rules=rules, timeout_s=timeout_s))

    ok_rule = InterpreterRule(api_versions=["apps/v1"], kinds=["*"],
                              operations=["*"])
    cp.store.create(mk("http://127.0.0.1:9/x", [ok_rule]))

    import pytest
    with pytest.raises(AdmissionDenied):
        cp.store.create(mk("ftp://nope", [ok_rule], name="bad-scheme"))
    with pytest.raises(AdmissionDenied):
        cp.store.create(mk("local:x", [], name="no-rules"))
    with pytest.raises(AdmissionDenied):
        cp.store.create(mk("local:x", [InterpreterRule()], name="empty-rule"))
    with pytest.raises(AdmissionDenied):
        cp.store.create(mk("local:x", [ok_rule], timeout_s=0, name="bad-timeout"))


def test_interpreter_webhook_empty_operations_denied():
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.config import (
        InterpreterRule,
        ResourceInterpreterWebhook,
        ResourceInterpreterWebhookSpec,
    )
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.webhook.admission import AdmissionDenied

    import pytest
    cp = ControlPlane()
    with pytest.raises(AdmissionDenied):
        cp.store.create(ResourceInterpreterWebhook(
            metadata=ObjectMeta(name="no-ops"),
            spec=ResourceInterpreterWebhookSpec(
                endpoint="local:x",
                rules=[InterpreterRule(api_versions=["apps/v1"],
                                       kinds=["*"], operations=[])])))


def test_federated_hpa_validation():
    """FederatedHPA admission: structural bounds + metric-target coherence
    (a mismatched target type/value field must be rejected at admission,
    not silently held at current replicas by the controller)."""
    from karmada_tpu.models.autoscaling import (
        CrossVersionObjectReference,
        ExternalMetricSource,
        FederatedHPA,
        FederatedHPASpec,
        MetricSpec,
        MetricTarget,
        PodsMetricSource,
        ResourceMetricSource,
    )
    from karmada_tpu.webhook.builtin import validate_federated_hpa

    def hpa(**kw):
        spec = FederatedHPASpec(
            scale_target_ref=CrossVersionObjectReference(
                "apps/v1", "Deployment", "web"),
            min_replicas=1, max_replicas=10,
            metrics=[MetricSpec(resource=ResourceMetricSource(
                name="cpu", target=MetricTarget(
                    type="Utilization", average_utilization=60)))],
        )
        for k, v in kw.items():
            setattr(spec, k, v)
        return FederatedHPA(metadata=ObjectMeta(name="h", namespace="ns"),
                            spec=spec)

    assert validate_federated_hpa("CREATE", hpa(), None) is None
    assert "maxReplicas" in validate_federated_hpa(
        "CREATE", hpa(max_replicas=0), None)
    assert "minReplicas" in validate_federated_hpa(
        "CREATE", hpa(min_replicas=12), None)
    # pods metric with the wrong target type (the default Utilization)
    bad_pods = hpa(metrics=[MetricSpec(type="Pods", pods=PodsMetricSource(
        metric="rps", target=MetricTarget(average_value=100)))])
    assert "not supported" in validate_federated_hpa("CREATE", bad_pods, None)
    # external AverageValue without the matching field
    bad_ext = hpa(metrics=[MetricSpec(type="External",
                                      external=ExternalMetricSource(
        metric="q", target=MetricTarget(type="AverageValue")))])
    assert "matching value field" in validate_federated_hpa(
        "CREATE", bad_ext, None)
    # empty metric spec
    assert "one of" in validate_federated_hpa(
        "CREATE", hpa(metrics=[MetricSpec(resource=None)]), None)
    # the store path enforces it end to end
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.webhook.admission import AdmissionDenied

    cp = ControlPlane()
    with pytest.raises(AdmissionDenied):
        cp.store.create(hpa(max_replicas=0))
