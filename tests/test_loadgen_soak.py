"""Sustained-traffic serve harness (karmada_tpu/loadgen) + the scheduler
admission / batch-formation machinery it closes the loop with.

Everything here runs on the injected VirtualClock with a FIXED service
model (per_binding 10ms, per_cycle 20ms virtual), so assertions about
dwell, shedding, and starvation are deterministic — the wall clock never
enters the math.  The compressed scenarios are tier-1; the heavy
variants ride the `slow` marker.
"""

from __future__ import annotations

import json
import urllib.request

import pytest

from karmada_tpu import obs
from karmada_tpu.loadgen import (
    LoadDriver,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    get_scenario,
    load_state,
)
from karmada_tpu.loadgen import driver as lg_driver
from karmada_tpu.loadgen import report as lg_report
from karmada_tpu.loadgen.arrival import (
    burst_rate,
    constant_rate,
    diurnal_rate,
    poisson_times,
)
from karmada_tpu.scheduler import metrics as sched_metrics
from karmada_tpu.scheduler.queue import (
    ADMIT_ADMITTED,
    ADMIT_DISPLACED,
    ADMIT_SHED,
    QueuedBindingInfo,
    SchedulingQueue,
)
from karmada_tpu.scheduler.service import Scheduler
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def run_scenario(name: str, seed: int = 1):
    clock = VirtualClock()
    model = ServiceModel()
    scenario = get_scenario(name)
    plane = ServeSlice(scenario, clock, model)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=seed)
    return scenario, driver, driver.run()


# -- arrival processes -------------------------------------------------------


def test_arrival_processes_deterministic_and_shaped():
    import random

    fn = constant_rate(50.0)
    a = poisson_times(fn, 50.0, 0.0, 10.0, random.Random(7))
    b = poisson_times(fn, 50.0, 0.0, 10.0, random.Random(7))
    assert a == b and a == sorted(a)  # seeded => replayable, ordered
    assert 350 < len(a) < 650  # ~500 expected
    # diurnal mean over whole periods ~= base; peak window denser
    d = diurnal_rate(50.0, 0.8, 10.0)
    times = poisson_times(d, 90.0, 0.0, 10.0, random.Random(7))
    assert 350 < len(times) < 650
    peak = sum(1 for t in times if 1.5 <= t < 3.5)   # sin>0 half
    trough = sum(1 for t in times if 6.5 <= t < 8.5)  # sin<0 half
    assert peak > 2 * trough
    # burst window dominates
    br = burst_rate(10.0, 200.0, 4.0, 6.0)
    times = poisson_times(br, 200.0, 0.0, 10.0, random.Random(7))
    in_burst = sum(1 for t in times if 4.0 <= t < 6.0)
    assert in_burst > 0.7 * len(times)


# -- admission gate (queue unit) --------------------------------------------


def admission_counts():
    return {d: sched_metrics.ADMISSION.value(decision=d)
            for d in (ADMIT_ADMITTED, ADMIT_SHED, ADMIT_DISPLACED)}


def test_admission_gate_sheds_and_displaces_exactly():
    clk = Clock()
    q = SchedulingQueue(now=clk, max_resident=3)
    base = admission_counts()
    decisions = [q.push(f"k{i}") for i in range(3)]       # fill the bound
    assert decisions == [ADMIT_ADMITTED] * 3
    assert q.push("k-overflow") == ADMIT_SHED             # equal prio: shed
    assert not q.has("k-overflow")
    # a resident key always re-admits (it already holds a slot)
    assert q.push("k1") == ADMIT_ADMITTED
    assert q.depths()["active"] == 3
    # higher priority displaces the lowest-priority resident
    assert q.push("vip", priority=5) == ADMIT_ADMITTED
    assert q.has("vip")
    assert q.depths()["active"] == 3  # bound held: someone was evicted
    # a second vip at the same priority as residents k* (0 < 5) displaces
    # another low entry; a vip-priority newcomer against an all-vip queue
    # would shed instead
    delta = {k: admission_counts()[k] - base[k] for k in base}
    # exactness: every push is exactly one of admitted/shed
    assert delta[ADMIT_ADMITTED] + delta[ADMIT_SHED] == 6
    assert delta[ADMIT_SHED] == 1
    assert delta[ADMIT_DISPLACED] == 1


def test_admission_equal_priority_never_thrashes():
    q = SchedulingQueue(max_resident=2)
    q.push("a", priority=1)
    q.push("b", priority=1)
    # equal-priority newcomers shed; residents keep their slots
    for i in range(5):
        assert q.push(f"c{i}", priority=1) == ADMIT_SHED
    assert q.has("a") and q.has("b")


def test_admission_unbounded_by_default():
    q = SchedulingQueue()
    for i in range(100):
        assert q.push(i) == ADMIT_ADMITTED
    assert q.depths()["active"] == 100


def test_admission_bound_holds_across_internal_moves():
    """Backoff/unschedulable -> active flushes are internal moves: they
    must never consume a new slot nor be refused."""
    clk = Clock()
    q = SchedulingQueue(now=clk, max_resident=2)
    q.push("a")
    q.push_backoff_if_not_present(QueuedBindingInfo(key="b", attempts=1))
    assert q.push("c") == ADMIT_SHED  # bound: a + b resident
    clk.t += 1.1
    assert q.flush_backoff() == 1     # internal move always succeeds
    assert q.depths() == {"active": 2, "backoff": 0, "unschedulable": 0}


def test_depth_counters_exact_under_mixed_transitions():
    """depths() is O(1) incremental counters now — verify they can never
    drift from the authoritative _where map across every transition
    kind (push/supersede/backoff/unschedulable/flush/pop/forget)."""
    import random as _random

    clk = Clock()
    q = SchedulingQueue(now=clk, max_resident=12)
    rng = _random.Random(3)
    for step in range(2000):
        k = f"k{rng.randrange(30)}"
        op = rng.randrange(6)
        if op == 0:
            q.push(k, priority=rng.randrange(3))
        elif op == 1:
            q.push_backoff_if_not_present(
                QueuedBindingInfo(key=k, attempts=rng.randrange(4)))
        elif op == 2:
            q.push_unschedulable_if_not_present(QueuedBindingInfo(key=k))
        elif op == 3:
            q.pop_ready(rng.randrange(1, 5))
        elif op == 4:
            q.forget(k)
        else:
            clk.t += rng.random() * 3
            q.flush_backoff()
            q.flush_unschedulable_leftover()
            if rng.random() < 0.2:
                q.move_all_to_active_or_backoff()
        truth = {"active": 0, "backoff": 0, "unschedulable": 0}
        for w in q._where.values():  # noqa: SLF001 — the ground truth
            truth[w] += 1
        assert q.depths() == truth, step


def test_zero_count_cluster_event_is_noop():
    """Regression: kill with count=0 used to slice alive[-0:] == the
    whole fleet and delete every cluster."""
    from karmada_tpu.loadgen.scenarios import ClusterEventSpec
    from karmada_tpu.models.cluster import Cluster

    clock = VirtualClock()
    model = ServiceModel()
    scenario = get_scenario("steady")
    plane = ServeSlice(scenario, clock, model)
    driver = LoadDriver(plane, scenario, clock=clock, model=model)
    before = len(list(plane.store.list(Cluster.KIND)))
    driver._apply_cluster_event(ClusterEventSpec(0.0, "kill", count=0))  # noqa: SLF001
    assert len(list(plane.store.list(Cluster.KIND))) == before


def test_weighted_percentiles_honor_strides():
    """Strided samples from large cycles must count at full weight: 512
    samples at stride 8 outweigh 100 unstrided quiet-cycle samples."""
    from karmada_tpu.loadgen.report import weighted_percentiles

    pairs = sorted([(0.01, 1)] * 100 + [(1.0, 8)] * 512)
    p = weighted_percentiles(pairs)
    assert p["count"] == 100 + 512 * 8
    assert p["p50"] == 1.0  # the strided mass dominates the median
    unweighted = weighted_percentiles([(v, 1) for v, _ in pairs])
    assert unweighted["count"] == 612


def test_storm_revive_restores_real_capacity():
    """Regression: revive used to resurrect default-shaped synthetic
    clusters; it must restore the ACTUAL killed cluster (spec+status),
    or a live plane's member comes back advertising the wrong capacity."""
    from karmada_tpu.loadgen.scenarios import ClusterEventSpec
    from karmada_tpu.models.cluster import Cluster
    from karmada_tpu.utils.quantity import Quantity

    clock = VirtualClock()
    model = ServiceModel()
    scenario = get_scenario("steady")
    plane = ServeSlice(scenario, clock, model)
    victim = f"lg-m{scenario.n_clusters - 1}"  # kill picks from the tail

    def shrink(c: Cluster) -> None:
        c.status.resource_summary.allocatable["cpu"] = Quantity.parse("7")
        c.metadata.labels["tier"] = "custom"

    plane.store.mutate(Cluster.KIND, "", victim, shrink)
    driver = LoadDriver(plane, scenario, clock=clock, model=model)
    driver._apply_cluster_event(ClusterEventSpec(0.0, "kill", count=1))  # noqa: SLF001
    assert plane.store.try_get(Cluster.KIND, "", victim) is None
    driver._apply_cluster_event(ClusterEventSpec(0.0, "revive", count=1))  # noqa: SLF001
    revived = plane.store.get(Cluster.KIND, "", victim)
    assert str(revived.status.resource_summary.allocatable["cpu"]) == "7"
    assert revived.metadata.labels["tier"] == "custom"


# -- dwell histogram + oldest-age introspection (satellite) ------------------


def test_pop_ready_records_dwell_by_origin():
    clk = Clock()
    q = SchedulingQueue(now=clk)
    h = sched_metrics.QUEUE_DWELL
    base_active = h.count(queue="active")
    base_backoff = h.count(queue="backoff")
    sum_active0 = h.sum(queue="active")
    q.push("fresh")
    clk.t += 5.0
    assert [i.key for i in q.pop_ready()] == ["fresh"]
    assert h.count(queue="active") == base_active + 1
    assert h.sum(queue="active") - sum_active0 == pytest.approx(5.0)
    # a flushed backoff entry pops with origin "backoff", dwell counted
    # from when it entered backoff (includes the parked wait)
    q.push_backoff_if_not_present(QueuedBindingInfo(key="bk", attempts=1))
    clk.t += 1.1
    q.flush_backoff()
    clk.t += 0.4
    infos = q.pop_ready()
    assert [i.origin for i in infos] == ["backoff"]
    assert h.count(queue="backoff") == base_backoff + 1


def test_oldest_ages_per_queue():
    clk = Clock()
    q = SchedulingQueue(now=clk)
    q.push("a")
    clk.t += 3.0
    q.push("b")
    q.push_unschedulable_if_not_present(QueuedBindingInfo(key="u"))
    clk.t += 2.0
    ages = q.oldest_ages()
    assert ages["active"] == pytest.approx(5.0)
    assert ages["unschedulable"] == pytest.approx(2.0)
    assert ages["backoff"] == 0.0
    assert q.oldest_active_age() == pytest.approx(5.0)


# -- batch formation ---------------------------------------------------------


def _service_scheduler(clk, batch_window=4, batch_deadline_s=None,
                       max_resident=None):
    store = ObjectStore()
    runtime = Runtime()
    sched = Scheduler(
        store, runtime, backend="serial", batch_window=batch_window,
        batch_deadline_s=batch_deadline_s,
        queue=SchedulingQueue(now=clk, max_resident=max_resident))
    return store, runtime, sched


def test_batch_formation_defers_until_deadline_or_size():
    clk = Clock()
    _, _, sched = _service_scheduler(clk, batch_window=4,
                                     batch_deadline_s=2.0)
    with sched._queue_lock:  # noqa: SLF001 — exercising the policy directly
        assert not sched._batch_ready_locked()  # never cut an empty cycle
        sched.queue.push(("ns", "a"))
        assert not sched._batch_ready_locked()  # 1 < window, age 0 < 2s
        clk.t += 2.0
        assert sched._batch_ready_locked()      # deadline reached
        sched.queue.pop_ready(4)
        for i in range(4):
            sched.queue.push(("ns", f"b{i}"))
        assert sched._batch_ready_locked()      # full batch cuts instantly


def test_batch_formation_legacy_without_deadline():
    clk = Clock()
    _, _, sched = _service_scheduler(clk, batch_window=4)
    with sched._queue_lock:  # noqa: SLF001
        assert not sched._batch_ready_locked()
        sched.queue.push(("ns", "a"))
        assert sched._batch_ready_locked()  # cut immediately (legacy)


@pytest.mark.soak
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_batch_formation_property_never_empty_never_over_window(seed):
    """Property over full soak runs: every cut cycle schedules at least
    one binding and never more than batch_window (the cycle spans carry
    the authoritative per-cycle batch size)."""
    scenario, driver, payload = run_scenario("steady", seed=seed)
    sizes = [s["attrs"]["bindings"]
             for s in lg_report._cycle_spans(driver.recorder)]  # noqa: SLF001
    assert sizes, "no cycles recorded"
    assert all(1 <= b <= scenario.batch_window for b in sizes)
    assert payload["cycles"]["empty"] == 0


def test_overload_enter_exit_and_explain_suppression():
    clk = Clock()
    _, _, sched = _service_scheduler(clk, batch_window=4,
                                     batch_deadline_s=1.0)
    sched.explain = 1.0
    sched._decisions = object()  # armed marker; never dereferenced
    assert sched._explain_sample() is not None
    # full-window cut with aged dwell: enter
    sched._update_overload([0.5, 0.6, 3.0, 3.5], popped=4, active_after=9)
    assert sched._overload
    assert sched_metrics.OVERLOAD_MODE.value() == 1.0
    assert sched._explain_sample() is None  # overload sheds explain first
    # widened deadline while overloaded
    with sched._queue_lock:  # noqa: SLF001
        sched.queue.push(("ns", "a"))
        clk.t += 2.0  # past 1x deadline, short of the widened 4x
        assert not sched._batch_ready_locked()
        clk.t += 2.5
        assert sched._batch_ready_locked()
        sched.queue.pop_ready(4)
    sched._update_overload([0.1, 0.2], popped=4, active_after=9)  # p95 under deadline
    assert not sched._overload
    assert sched._explain_sample() is not None


def test_overload_unlatches_on_sub_window_cut():
    """Regression: while overloaded, deadline cuts happen at the WIDENED
    deadline, so their p95 dwell can never pass the unwidened exit
    threshold — the mode used to latch on forever after a storm.  A
    sub-window cut (the backlog no longer fills a batch) must exit."""
    clk = Clock()
    _, _, sched = _service_scheduler(clk, batch_window=4,
                                     batch_deadline_s=1.0)
    sched._update_overload([3.0, 3.5, 4.0, 4.5], popped=4, active_after=9)
    assert sched._overload
    # a deferred no-cut invocation (popped 0) is the widened deadline
    # COALESCING, not a drain — it must not flap the mode off
    sched._update_overload([], popped=0, active_after=3)
    assert sched._overload
    # post-storm trickle: the cut is deadline-triggered at the widened
    # deadline (dwell ~4s > 1s exit threshold) but sub-window — exits
    sched._update_overload([4.0, 4.1], popped=2, active_after=9)
    assert not sched._overload
    assert sched_metrics.OVERLOAD_MODE.value() == 0.0
    # ...and so must the OTHER drain shape: the final cut of a backlog is
    # a full window with high dwell, but it empties the activeQ
    sched._update_overload([3.0, 3.5, 4.0, 4.5], popped=4, active_after=9)
    assert sched._overload
    sched._update_overload([4.0, 4.1, 4.2, 4.3], popped=4, active_after=0)
    assert not sched._overload


# -- unschedulable flush unification (satellite regression) ------------------


def _unschedulable_binding(name: str):
    """A binding the serial path routes to the unschedulable queue:
    dynamic-weight division demanding more replicas than the fleet has."""
    from karmada_tpu.models.policy import (
        ClusterPreferences,
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        Placement,
        REPLICA_DIVISION_WEIGHTED,
        REPLICA_SCHEDULING_DIVIDED,
        ReplicaSchedulingStrategy,
    )

    rb = lg_driver.build_binding(name)
    rb.spec.replicas = 10_000_000
    rb.spec.placement = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
    return rb


def test_unschedulable_leftover_flushes_on_cycle_path():
    """Regression: flush_unschedulable_leftover used to run only on the
    periodic path, so a parked binding could outlive its budget by a full
    flush interval; now any cycle (pump only, NO periodic tick) flushes."""
    clk = Clock()
    store, runtime, sched = _service_scheduler(clk, batch_window=16)
    store.create(lg_driver.build_cluster("m1"))
    runtime.pump()
    store.create(_unschedulable_binding("parked"))
    runtime.pump()
    key = ("loadgen", "parked")
    assert sched.queue.depths()["unschedulable"] == 1
    assert sched.queue._info[key].attempts == 1  # noqa: SLF001
    # age past the budget, then trigger a cycle with an unrelated binding
    # event — NOT the periodic flush (pump never runs periodic hooks)
    clk.t += sched.queue.max_in_unschedulable_s + 1
    store.create(lg_driver.build_binding("fresh"))
    runtime.pump()
    assert sched.queue._info[key].attempts == 2  # noqa: SLF001 — retried


# -- compressed soak scenarios (the tentpole acceptance) ---------------------


@pytest.mark.soak
def test_steady_soak_no_overload_slo():
    """The no-overload reference point: nothing sheds, every binding
    schedules, p99 dwell stays under the configured deadline, and no
    binding starves (dwell > deadline x 2).  Runs with the runtime race
    detector armed — the compressed soak doubles as the lock-detector's
    steady-traffic acceptance run (zero inversions, zero watchdog
    trips)."""
    from karmada_tpu.analysis import guards
    from karmada_tpu.utils import locks

    was_armed = guards.armed()
    locks.reset_for_tests()
    inv0 = locks._INVERSIONS.total()  # noqa: SLF001
    trips0 = locks._TRIPS.total()  # noqa: SLF001
    guards.arm()
    wd = locks.LockWatchdog(threshold_s=5.0, poll_s=0.2).start()
    try:
        scenario, driver, p = run_scenario("steady")
    finally:
        wd.stop()
        guards.arm(was_armed)
    assert locks._INVERSIONS.total() - inv0 == 0, (  # noqa: SLF001
        locks.state_payload()["inversions"])
    assert locks._TRIPS.total() - trips0 == 0  # noqa: SLF001
    deadline = scenario.deadline_s(driver.model)
    assert p["admission"]["shed"] == 0
    assert p["admission"]["displaced"] == 0
    assert p["scheduled"] == p["injected"] > 200
    assert p["residual_queue"] == {"active": 0, "backoff": 0,
                                   "unschedulable": 0}
    assert p["queue_dwell_s"]["p99"] < deadline
    assert p["queue_dwell_s"]["max"] <= deadline * 2  # zero starvation
    assert p["starvation"]["overload_entered"] is False
    # span-derived latency percentiles exist and are ordered
    lat = p["schedule_latency_s"]
    assert lat["count"] == p["injected"]
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]


@pytest.mark.soak
def test_diurnal_soak_bounded_dwell():
    scenario, driver, p = run_scenario("diurnal")
    deadline = scenario.deadline_s(driver.model)
    assert p["admission"]["shed"] == 0
    assert p["scheduled"] == p["injected"]
    # the 1.08x peak may ride past the deadline briefly but never starves
    assert p["queue_dwell_s"]["max"] <= deadline * 2
    assert p["residual_queue"]["active"] == 0


@pytest.mark.soak
def test_storm_soak_sheds_and_stays_bounded():
    """2x-capacity burst + cluster kills: the admission gate must shed
    the excess, hold the resident bound, and enter overload degradation;
    admitted bindings still never starve."""
    scenario, driver, p = run_scenario("storm")
    bound = scenario.admission_limit()
    deadline = scenario.deadline_s(driver.model)
    assert p["admission"]["shed"] > 0
    # the hard resident ceiling: the admission bound plus one in-flight
    # batch (gate-exempt result-patch echoes + ungated failure re-adds
    # reclaim slots concurrent arrivals may have refilled — documented
    # in scheduler/queue.py)
    assert (max(p["queue_depth"]["max"].values())
            <= bound + scenario.batch_window)
    assert p["starvation"]["overload_entered"] is True
    assert p["reschedules"] > 0  # the kills evicted real placements
    # conservation: every injected binding either scheduled or ended shed
    # (the queue empties: residuals are zero)
    assert p["residual_queue"] == {"active": 0, "backoff": 0,
                                   "unschedulable": 0}
    never_scheduled = p["injected"] - p["scheduled"]
    assert never_scheduled > 0
    assert p["admission"]["shed"] >= never_scheduled
    # admitted load stays bounded-latency even through the storm: a full
    # resident backlog drains in bound/capacity seconds, plus the
    # overload-widened deadline of slack
    sched = driver.plane.scheduler
    dwell_cap = (bound * driver.model.per_binding_s
                 + deadline * sched.overload_deadline_factor)
    assert p["queue_dwell_s"]["max"] <= dwell_cap


@pytest.mark.soak
def test_churn_soak_survives_capacity_flaps():
    scenario, driver, p = run_scenario("churn")
    assert p["scheduled"] == p["injected"]
    assert p["residual_queue"]["active"] == 0
    assert p["admission"]["shed"] == 0


@pytest.mark.soak
def test_soak_determinism_same_seed_same_traffic():
    _, d1, p1 = run_scenario("steady", seed=42)
    _, d2, p2 = run_scenario("steady", seed=42)
    assert d1._arrivals == d2._arrivals  # noqa: SLF001
    assert p1["injected"] == p2["injected"]
    assert p1["admission"] == p2["admission"]
    assert p1["queue_dwell_s"] == p2["queue_dwell_s"]


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("name", ["storm-heavy", "diurnal-heavy"])
def test_heavy_soaks(name):
    scenario, driver, p = run_scenario(name)
    assert p["injected"] > 4000
    assert (max(p["queue_depth"]["max"].values())
            <= scenario.admission_limit() + scenario.batch_window)
    assert p["residual_queue"]["active"] == 0


# -- report + exposure surfaces ----------------------------------------------


def test_soak_report_shape_and_stage_utilization():
    _, driver, p = run_scenario("steady")
    assert p["version"] == 1
    for key in ("scenario", "model", "arrival", "schedule_latency_s",
                "queue_dwell_s", "driver_latency_s", "admission",
                "queue_depth", "starvation", "cycles", "stage_utilization",
                "injected", "scheduled"):
        assert key in p, key
    # serial-backend cycles spend their time in the serial span; the
    # utilization table attributes it
    assert "scheduler.cycle" in p["stage_utilization"]
    assert "scheduler.serial" in p["stage_utilization"]
    assert p["stage_utilization"]["scheduler.serial"]["of_cycle"] <= 1.0
    json.dumps(p)  # the payload is a valid JSON document end to end


def test_driver_restores_tracer_and_schedule_batch():
    clock = VirtualClock()
    model = ServiceModel()
    scenario = get_scenario("steady")
    plane = ServeSlice(scenario, clock, model)
    prev_recorder = obs.TRACER.recorder
    driver = LoadDriver(plane, scenario, clock=clock, model=model)
    driver.run()
    # the wrap is gone: the class method shows through again
    assert "schedule_batch" not in vars(plane.scheduler)
    assert obs.TRACER.recorder is prev_recorder  # tracer state restored
    assert load_state() == {"enabled": False}  # deregistered


def test_debug_load_endpoint_live_and_idle():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    srv = ObservabilityServer()
    url = srv.start(port=0)
    try:
        with urllib.request.urlopen(url + "/debug/load", timeout=5) as r:
            assert json.loads(r.read()) == {"enabled": False}
        clock = VirtualClock()
        model = ServiceModel()
        scenario = get_scenario("steady")
        plane = ServeSlice(scenario, clock, model)
        driver = LoadDriver(plane, scenario, clock=clock, model=model)
        driver._install()  # noqa: SLF001 — live-state window under test
        try:
            with urllib.request.urlopen(url + "/debug/load", timeout=5) as r:
                state = json.loads(r.read())
            assert state["enabled"] is True
            assert state["scenario"] == "steady"
            assert state["queue"]["admission_limit"] == \
                scenario.admission_limit()
            # the human rendering covers the same payload
            text = lg_report.render_load_state(state)
            assert "steady" in text and "admission" in text
        finally:
            driver._uninstall()  # noqa: SLF001
        with urllib.request.urlopen(url + "/debug/load", timeout=5) as r:
            assert json.loads(r.read()) == {"enabled": False}
    finally:
        srv.stop()


def test_cli_loadgen_catalog_and_rehearsal(capsys):
    from karmada_tpu import cli

    assert cli.main(["loadgen"]) == 0
    out = capsys.readouterr().out
    for name in ("steady", "storm", "diurnal", "churn"):
        assert name in out
    assert cli.main(["loadgen", "no-such-scenario"]) == 1
    assert "unknown scenario" in capsys.readouterr().err
    assert cli.main(["loadgen", "steady", "--seed", "3"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["scenario"] == "steady"
    assert payload["scheduled"] == payload["injected"]


def test_oldest_age_gauge_exported_by_periodic_flush():
    clk = Clock()
    store, runtime, sched = _service_scheduler(clk, batch_window=4,
                                               batch_deadline_s=100.0)
    store.create(lg_driver.build_cluster("m1"))
    runtime.pump()
    store.create(lg_driver.build_binding("waiting"))
    runtime.pump()  # deferred: deadline far away, batch not full
    clk.t += 7.0
    sched._periodic_flush()  # noqa: SLF001 — the tick the gauge rides
    assert sched_metrics.QUEUE_OLDEST_AGE.value(queue="active") >= 7.0


def test_control_plane_duck_types_as_loadgen_plane():
    """The driver runs against a full ControlPlane through the exact
    store/worker paths serve mode uses (members, works, executors all
    live) — ServeSlice is just the fast slice of the same surface."""
    from karmada_tpu.e2e import ControlPlane

    cp = ControlPlane(backend="serial", batch_window=16,
                      batch_deadline_s=0.02)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    # the synthetic bindings reference one shared template so the binding
    # controller can render real Works into the member clusters
    cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
              "metadata": {"name": "lg-shared",
                           "namespace": lg_driver.LOADGEN_NS},
              "spec": {"replicas": 1, "template": {"spec": {
                  "containers": [{"name": "c"}]}}}})
    scenario = get_scenario("steady")
    # tiny run: 40 bindings through the full plane
    import dataclasses

    scenario = dataclasses.replace(scenario, n_bindings=40)
    driver = LoadDriver(cp, scenario, seed=5, resource_name="lg-shared")
    p = driver.run()
    assert p["scheduled"] == p["injected"] > 20
    assert p["admission"]["shed"] == 0
    # the plane really propagated: works rendered from the shared
    # template landed in the member execution namespaces
    works = [w for w in cp.store.list("Work")
             if w.metadata.name.startswith("lg-shared")]
    assert works
