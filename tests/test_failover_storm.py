"""The failover chain end-to-end under a virtual-clock storm.

Closes the e2e half of ROADMAP item 5 for controllers/{cluster,failover,
lease}.py: two member clusters fail SIMULTANEOUSLY and the whole chain —
Ready=False -> not-ready NoExecute taint -> toleration expiry -> graceful
eviction whose task drains only after the replacement replicas report
healthy -> the scheduler topping the lost replicas back up on the
survivors — runs on an injected clock, so every deadline is exact and
the storm replays deterministically.  A flapping cluster (recovered
before its toleration expires) must come through the same storm
untouched.
"""

from __future__ import annotations

from karmada_tpu.controllers.binding import work_name
from karmada_tpu.controllers.failover import TAINT_NOT_READY
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding, Work


def _policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    )


def _deployment(replicas: int):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "app", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {
            "containers": [{"name": "app", "image": "app:1",
                            "resources": {"requests": {"cpu": "500m",
                                                       "memory": "1Gi"}}}],
        }}},
    }


def _rb(cp) -> ResourceBinding:
    return cp.store.get(ResourceBinding.KIND, "default", "app-deployment")


def test_failover_chain_under_virtual_clock_storm():
    clock = {"now": 1000.0}
    cp = ControlPlane(clock=lambda: clock["now"],
                      eviction_grace_period_s=3600)
    for m in ("m1", "m2", "m3", "m4"):
        cp.add_member(m, cpu_milli=64_000)
    cp.apply_policy(_policy())
    cp.apply(_deployment(8))
    cp.tick()
    before = {t.name: t.replicas for t in _rb(cp).spec.clusters}
    assert sum(before.values()) == 8 and len(before) == 4

    # -- the storm: two clusters fail in the same instant -------------------
    cp.member("m3").healthy = False
    cp.member("m4").healthy = False
    cp.tick()
    for m in ("m3", "m4"):
        cluster = cp.store.get(Cluster.KIND, "", m)
        assert any(t.key == TAINT_NOT_READY for t in cluster.spec.taints), \
            f"{m}: Ready=False must add the not-ready NoExecute taint"
    # the defaulted 300s toleration holds the placements for now
    rb = _rb(cp)
    assert {t.name for t in rb.spec.clusters} >= {"m3", "m4"}
    assert not rb.spec.graceful_eviction_tasks

    # -- flap leg: m4 recovers before its toleration expires ----------------
    clock["now"] += 120.0
    cp.member("m4").healthy = True
    cp.tick()
    cluster = cp.store.get(Cluster.KIND, "", "m4")
    assert not any(t.key == TAINT_NOT_READY for t in cluster.spec.taints)

    # -- toleration expiry evicts the sustained failure ---------------------
    clock["now"] += 301.0
    cp.tick()
    rb = _rb(cp)
    names = {t.name: t.replicas for t in rb.spec.clusters}
    assert "m3" not in names, "toleration expired: m3 must be evicted"
    assert "m4" in names, "the flapped cluster must survive the storm"
    # the scheduler topped the lost replicas back up on the survivors
    assert sum(names.values()) == 8
    # graceful eviction created the drain task, and the stale Work
    # survives until the replacement reports healthy (grace period is 1h,
    # so only replacement health can drain it)
    task_seen = bool(rb.spec.graceful_eviction_tasks)
    if task_seen:
        assert rb.spec.graceful_eviction_tasks[0].from_cluster == "m3"
        assert cp.store.try_get(Work.KIND, "karmada-es-m3",
                                work_name(rb)) is not None

    # -- replacement reports healthy: the eviction task drains --------------
    cp.tick()
    cp.tick()
    rb = _rb(cp)
    assert not rb.spec.graceful_eviction_tasks
    assert cp.store.try_get(Work.KIND, "karmada-es-m3", work_name(rb)) is None

    # -- recovery: m3 rejoins and is schedulable again ----------------------
    cp.member("m3").healthy = True
    cp.tick()
    cluster = cp.store.get(Cluster.KIND, "", "m3")
    assert not any(t.key == TAINT_NOT_READY for t in cluster.spec.taints)


def test_storm_eviction_pacing_is_rate_limited():
    """A zone-wide storm's evictions flow through the rate-limited queue
    (cluster/eviction_worker.go semantics): with eviction_rate tiny, one
    tick drains at most the accrued token allowance instead of
    stampeding every binding through rescheduling at once."""
    clock = {"now": 1000.0}
    cp = ControlPlane(clock=lambda: clock["now"], eviction_rate=1.0,
                      eviction_grace_period_s=0,
                      default_toleration_seconds=None)
    cp.add_member("m1", cpu_milli=64_000)
    cp.add_member("m2", cpu_milli=64_000)
    cp.apply_policy(_policy())
    # several workloads so the kill enqueues several evictions
    for i in range(4):
        d = _deployment(2)
        d["metadata"]["name"] = f"app{i}"
        cp.apply(d)
    cp.tick()

    cp.member("m2").healthy = False
    cp.tick()
    # untolerated taint: every binding targeting m2 is due immediately,
    # but the paced queue drains them one token at a time
    pending_after_first = cp.eviction_queue.pending()
    total_evictions = 4
    drained_first = total_evictions - pending_after_first
    assert drained_first < total_evictions, \
        "rate 1/s must not drain the whole storm in one tick"
    # accrue tokens on the virtual clock until the queue empties
    for _ in range(8):
        clock["now"] += 1.0
        cp.tick()
    assert cp.eviction_queue.pending() == 0
    for i in range(4):
        rb = cp.store.get(ResourceBinding.KIND, "default",
                          f"app{i}-deployment")
        assert not any(t.name == "m2" for t in rb.spec.clusters)
        assert sum(t.replicas for t in rb.spec.clusters) == 2
