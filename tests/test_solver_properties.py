"""Property tests for the device solver (SURVEY §7 "Hard parts"):
invariants asserted directly, independent of serial parity — golden tests
cannot catch a bug present in BOTH paths.

  P1  sum(assigned replicas) == spec.replicas for every OK Divided binding
  P2  assigned clusters respect the feasibility mask (affinity subsets,
      deleting clusters, API enablement)
  P3  |assigned| <= cluster-spread MaxGroups when a cluster spread
      constraint governs an Aggregated division.  (MinGroups bounds the
      candidate SELECTION, not the final assignment: Aggregated division
      deliberately concentrates onto the fewest clusters that fit,
      division_algorithm.go:80-90 — so no lower bound holds here.)
  P4  Duplicated assigns exactly spec.replicas to every selected cluster
"""

from __future__ import annotations

import random

import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.policy import (
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
)
from karmada_tpu.ops import tensors
from karmada_tpu.ops.solver import solve_compact
from karmada_tpu.ops.spread import solve_spread


def run_device(items, clusters):
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    cache = tensors.EncoderCache()
    batch = tensors.encode_batch(items, cindex, est, cache=cache)
    idx, val, status, _ = solve_compact(batch, waves=4)
    spread_idx = [i for i in range(len(items))
                  if batch.route[i] == tensors.ROUTE_DEVICE_SPREAD]
    spread_res = solve_spread(batch, items, spread_idx, waves=4)
    decoded = tensors.decode_compact(batch, idx, val, status)
    out = []
    for i in range(len(items)):
        if i in spread_res:
            out.append(spread_res[i])
        elif batch.route[i] == tensors.ROUTE_DEVICE:
            out.append(decoded[i])
        else:
            out.append(None)  # host-routed: out of scope here
    return out, batch


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_divided_sum_and_mask_and_spread(seed):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, 64)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 256, placements)
    results, batch = run_device(items, clusters)

    checked_sum = checked_mask = checked_spread = checked_dup = 0
    for (spec, _), res in zip(items, results):
        if res is None or isinstance(res, Exception):
            continue
        placement = spec.placement
        strategy = placement.replica_scheduling
        names = {tc.name for tc in res}

        # P2: the feasibility mask — affinity subset + deleting + enablement
        if placement.cluster_affinity is not None:
            allowed = set(placement.cluster_affinity.cluster_names)
            assert names <= allowed, (spec.resource.name, names - allowed)
            checked_mask += 1
        by_name = {c.name: c for c in clusters}
        for n in names:
            assert not by_name[n].metadata.deleting

        if strategy.replica_scheduling_type == REPLICA_SCHEDULING_DUPLICATED:
            # P4: full copy per selected cluster
            for tc in res:
                assert tc.replicas == spec.replicas
            checked_dup += 1
            continue

        # P1: division preserves the replica total
        total = sum(tc.replicas for tc in res)
        assert total == spec.replicas, (spec.resource.name, total, spec.replicas)
        checked_sum += 1

        # P3: cluster spread bounds for Aggregated
        sc = next((s for s in placement.spread_constraints
                   if s.spread_by_field == SPREAD_BY_FIELD_CLUSTER), None)
        if (sc is not None
                and strategy.replica_division_preference == REPLICA_DIVISION_AGGREGATED):
            assert len(names) <= sc.max_groups
            checked_spread += 1

    # the scenario mix must actually exercise every property
    assert checked_sum > 20 and checked_mask > 10
    assert checked_spread > 5 and checked_dup > 10
