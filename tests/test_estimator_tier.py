"""Accurate estimator tier: server math, wire transports, clients.

Covers the reference pkg/estimator contract (SURVEY.md section 2.5): the
node-level server, per-cluster RPC fan-out with the UnauthenticReplica
sentinel, the unschedulable-replica path, and the capacity-snapshot
shipping that replaces per-call RPCs for the batched scheduler.
"""

import pytest

from karmada_tpu.estimator.client import AccurateEstimatorClient, SnapshotEstimator
from karmada_tpu.estimator.server import AccurateEstimatorServer
from karmada_tpu.estimator.wire import LocalTransport, serve_tcp, TcpTransport
from karmada_tpu.members.member import FakeMemberCluster, FakeNode
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.work import NodeClaim, ReplicaRequirements
from karmada_tpu.utils.quantity import Quantity


def member_with_nodes():
    return FakeMemberCluster(name="m1", nodes=[
        FakeNode(name="n1", cpu_milli=4000, memory_milli=Quantity.parse("8Gi").milli,
                 pods=10, labels={"tier": "fast"}),
        FakeNode(name="n2", cpu_milli=2000, memory_milli=Quantity.parse("4Gi").milli,
                 pods=10),
    ])


def req(cpu="1", memory="1Gi", selector=None):
    return ReplicaRequirements(
        resource_request={"cpu": Quantity.parse(cpu),
                          "memory": Quantity.parse(memory)},
        node_claim=NodeClaim(node_selector=selector) if selector else None,
    )


def test_node_level_estimate():
    server = AccurateEstimatorServer(member_with_nodes())
    # n1 fits min(4, 8) = 4; n2 fits min(2, 4) = 2
    assert server.max_available_replicas(req()) == 6


def test_node_selector_filters_nodes():
    server = AccurateEstimatorServer(member_with_nodes())
    assert server.max_available_replicas(req(selector={"tier": "fast"})) == 4


def test_applied_workloads_consume_capacity():
    member = member_with_nodes()
    member.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "eater", "namespace": "default"},
        "spec": {"replicas": 3, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "1",
                                                     "memory": "1Gi"}}}]}}},
    })
    server = AccurateEstimatorServer(member)
    assert server.max_available_replicas(req()) == 3


def test_unschedulable_replicas_counted():
    member = FakeMemberCluster(name="m1", cpu_allocatable_milli=2000)
    member.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"replicas": 5, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "1"}}}]}}},
    })
    server = AccurateEstimatorServer(member)
    assert server.unschedulable_replicas("Deployment", "default", "big") == 3


def test_accurate_client_min_merge_and_sentinel():
    m1 = member_with_nodes()
    client = AccurateEstimatorClient()
    client.register("m1", LocalTransport(AccurateEstimatorServer(m1).handle))
    clusters = [Cluster(metadata=ObjectMeta(name="m1")),
                Cluster(metadata=ObjectMeta(name="m2"))]  # m2 has no estimator
    out = client.max_available_replicas(clusters, req())
    got = {t.name: t.replicas for t in out}
    assert got == {"m1": 6, "m2": -1}


def test_tcp_transport_roundtrip():
    server_impl = AccurateEstimatorServer(member_with_nodes())
    srv = serve_tcp(server_impl.handle)
    host, port = srv.server_address
    try:
        client = AccurateEstimatorClient()
        client.register("m1", TcpTransport(host, port))
        clusters = [Cluster(metadata=ObjectMeta(name="m1"))]
        out = client.max_available_replicas(clusters, req())
        assert out[0].replicas == 6
        assert client.unschedulable_replicas("m1", "Deployment", "default", "x") == 0
    finally:
        srv.shutdown()


def test_snapshot_estimator_matches_accurate():
    member = member_with_nodes()
    client = AccurateEstimatorClient()
    client.register("m1", LocalTransport(AccurateEstimatorServer(member).handle))
    snap = SnapshotEstimator(client)
    clusters = [Cluster(metadata=ObjectMeta(name="m1"))]
    for r in (req(), req(cpu="500m", memory="512Mi"), None):
        accurate = client.max_available_replicas(clusters, r)[0].replicas
        local = snap.max_available_replicas(clusters, r)[0].replicas
        assert local == accurate, r


def test_scheduler_uses_accurate_estimator():
    """The estimator plugs into the serial cal_available min-merge."""
    from karmada_tpu.ops import serial
    from karmada_tpu.estimator.general import GeneralEstimator
    from karmada_tpu.models.cluster import APIEnablement, ClusterStatus
    from karmada_tpu.models.work import ObjectReference, ResourceBindingSpec

    member = member_with_nodes()
    client = AccurateEstimatorClient()
    client.register("m1", LocalTransport(AccurateEstimatorServer(member).handle))

    cluster = Cluster(
        metadata=ObjectMeta(name="m1"),
        status=ClusterStatus(
            api_enablements=[APIEnablement("apps/v1", ["Deployment"])],
            resource_summary=member.resource_summary(),
        ),
    )
    spec = ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 name="x", uid="u"),
        replicas=3, replica_requirements=req(),
    )
    cal = serial.make_cal_available([GeneralEstimator(), client])
    out = cal([cluster], spec)
    # general says min(cpu 6, mem 12, pods 20)=6; accurate node-level says 6
    assert out[0].replicas == 6


def test_resource_quota_plugin_caps_estimate():
    """server/framework/plugins/resourcequota behind ResourceQuotaEstimate:
    the member namespace's ResourceQuota headroom caps the estimate."""
    from karmada_tpu.estimator.server import AccurateEstimatorServer
    from karmada_tpu.members.member import FakeMemberCluster
    from karmada_tpu.models.work import ReplicaRequirements
    from karmada_tpu.utils.features import FeatureGates
    from karmada_tpu.utils.quantity import Quantity

    member = FakeMemberCluster(name="m1", cpu_allocatable_milli=64_000)
    member.apply({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "team-a", "namespace": "default"},
        "spec": {"hard": {"cpu": "2", "memory": "8Gi"}},
        "status": {"used": {"cpu": "500m"}},
    })
    req = ReplicaRequirements(
        resource_request={"cpu": Quantity.parse("500m"),
                          "memory": Quantity.parse("1Gi")},
        namespace="default",
    )
    gated_off = AccurateEstimatorServer(member, gates=FeatureGates())
    assert gated_off.max_available_replicas(req) > 3  # node capacity only

    gates = FeatureGates({"ResourceQuotaEstimate": True})
    server = AccurateEstimatorServer(member, gates=gates)
    # quota headroom: (2000m - 500m) / 500m = 3 replicas
    assert server.max_available_replicas(req) == 3
    # other namespaces are unaffected
    req_other = ReplicaRequirements(
        resource_request={"cpu": Quantity.parse("500m")}, namespace="prod"
    )
    assert server.max_available_replicas(req_other) > 3
