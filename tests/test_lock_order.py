"""Concurrency vet (ISSUE-19): the static lock-order pass over seeded
fixtures, the waiver grammar, and the armed runtime race detector
(utils/locks) — scripted cross-thread mutation, A/B-B/A inversion,
deadlock-watchdog trip, disarmed zero-overhead."""

import textwrap
import threading

import pytest

from karmada_tpu.analysis import guards
from karmada_tpu.analysis.vet import run_vet
from karmada_tpu.utils import locks
from karmada_tpu.utils.metrics import REGISTRY


def _vet(tmp_path, name, src, extra=None):
    (tmp_path / name).write_text(textwrap.dedent(src))
    for fname, fsrc in (extra or {}).items():
        (tmp_path / fname).write_text(textwrap.dedent(fsrc))
    return run_vet([str(tmp_path)])


@pytest.fixture
def armed():
    """Arm the detector for one test; restore and clear edge state."""
    was = guards.armed()
    locks.reset_for_tests()
    guards.arm()
    yield
    guards.arm(was)
    locks.reset_for_tests()


# -- static: lock-order cycles -----------------------------------------------

CYCLE_BAD = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def one():
        with A:
            with B:
                pass

    def two():
        with B:
            with A:
                pass
"""

CYCLE_FIXED = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def one():
        with A:
            with B:
                pass

    def two():
        with A:
            with B:
                pass
"""


def test_lock_order_catches_two_lock_cycle(tmp_path):
    rep = _vet(tmp_path, "m.py", CYCLE_BAD)
    cyc = [f for f in rep.findings if f.rule == "lock-order"]
    assert len(cyc) == 1, [f.message for f in rep.findings]
    assert "cycle" in cyc[0].message
    assert "m.py:A" in cyc[0].message and "m.py:B" in cyc[0].message


def test_lock_order_consistent_order_is_clean(tmp_path):
    rep = _vet(tmp_path, "m.py", CYCLE_FIXED)
    assert [f for f in rep.findings if f.rule == "lock-order"] == []


TRANSITIVE_CYCLE = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def take_a():
        with A:
            pass

    def one():
        with A:
            with B:
                pass

    def two():
        with B:
            take_a()
"""


def test_lock_order_follows_call_closure(tmp_path):
    """The cycle only exists through the called function's acquire."""
    rep = _vet(tmp_path, "m.py", TRANSITIVE_CYCLE)
    cyc = [f for f in rep.findings if f.rule == "lock-order"]
    assert len(cyc) == 1, [f.message for f in rep.findings]


CROSS_MODULE = {
    "helper.py": """
        import threading

        A = threading.Lock()

        def take_a():
            with A:
                pass
    """,
}

CROSS_MAIN = """
    import threading
    from helper import take_a

    B = threading.Lock()

    def one():
        import helper
        with helper.B:  # unknown receiver: skipped, not crashed
            pass

    def two():
        with B:
            take_a()
"""


def test_lock_order_cross_module_edges(tmp_path):
    """Edges reach through from-imports (trace_safety's resolver); a
    consistent cross-module order stays clean."""
    rep = _vet(tmp_path, "main.py", CROSS_MAIN, extra=CROSS_MODULE)
    assert [f for f in rep.findings if f.rule == "lock-order"] == []


SELF_DEADLOCK = """
    import threading

    L = threading.Lock()

    def helper():
        with L:
            pass

    def outer():
        with L:
            helper()
"""

SELF_RLOCK_OK = SELF_DEADLOCK.replace("threading.Lock()",
                                      "threading.RLock()")


def test_lock_order_nonreentrant_self_deadlock(tmp_path):
    rep = _vet(tmp_path, "m.py", SELF_DEADLOCK)
    cyc = [f for f in rep.findings if f.rule == "lock-order"]
    assert len(cyc) == 1
    assert "re-acquired" in cyc[0].message


def test_lock_order_rlock_reacquire_is_fine(tmp_path):
    rep = _vet(tmp_path, "m.py", SELF_RLOCK_OK)
    assert [f for f in rep.findings if f.rule == "lock-order"] == []


NESTED_DEF_OK = """
    import threading

    L = threading.Lock()

    def arm_timer():
        def fire():
            with L:
                pass
        with L:
            t = threading.Timer(0.1, fire)
            t.start()
"""


def test_lock_order_nested_def_not_charged_to_parent(tmp_path):
    """A closure's acquire is deferred work — passing it to a timer
    under the lock is NOT a self-deadlock (the scheduler/service
    _arm_cut_timer_locked shape)."""
    rep = _vet(tmp_path, "m.py", NESTED_DEF_OK)
    assert [f for f in rep.findings if f.rule == "lock-order"] == []


CONDITION_ALIAS = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)

        def a_then_b(self):
            with self._lock:
                with self._cond:
                    pass
"""


def test_lock_order_condition_shares_wrapped_lock_identity(tmp_path):
    """Condition(self._lock) IS self._lock: nesting them is the length-1
    self-deadlock, not a benign two-lock edge."""
    rep = _vet(tmp_path, "m.py", CONDITION_ALIAS)
    cyc = [f for f in rep.findings if f.rule == "lock-order"]
    assert len(cyc) == 1
    assert "re-acquired" in cyc[0].message


# -- static: blocking calls under a held lock --------------------------------

BLOCKING_BAD = """
    import threading
    import time

    L = threading.Lock()

    def tick(thread):
        with L:
            time.sleep(0.5)
            thread.join()

    def device_wait(arr):
        with L:
            arr.block_until_ready()
"""

BLOCKING_FIXED = """
    import threading
    import time

    L = threading.Lock()

    def tick(thread, parts):
        with L:
            snapshot = list(parts)
        time.sleep(0.5)
        thread.join()
        return ",".join(snapshot)  # str.join: one positional arg, fine
"""


def test_lock_blocking_call_catches_sleep_join_device_sync(tmp_path):
    rep = _vet(tmp_path, "m.py", BLOCKING_BAD)
    blk = [f for f in rep.findings if f.rule == "lock-blocking-call"]
    descs = " | ".join(f.message for f in blk)
    assert len(blk) == 3, descs
    assert ".sleep()" in descs and ".join()" in descs \
        and "block_until_ready" in descs


def test_lock_blocking_call_fixed_is_clean(tmp_path):
    rep = _vet(tmp_path, "m.py", BLOCKING_FIXED)
    assert [f for f in rep.findings if f.rule == "lock-blocking-call"] == []


TRANSITIVE_BLOCKING = """
    import threading
    import time

    L = threading.Lock()

    def slow_path():
        time.sleep(1.0)

    def fast_path():
        with L:
            slow_path()
"""


def test_lock_blocking_call_transitive_anchors_at_call_site(tmp_path):
    rep = _vet(tmp_path, "m.py", TRANSITIVE_BLOCKING)
    blk = [f for f in rep.findings if f.rule == "lock-blocking-call"]
    assert len(blk) == 1
    # the finding anchors where the lock-holder calls out, so a waiver
    # at that line covers the edge
    assert "slow_path" in blk[0].message
    assert blk[0].line == 12


COND_WAIT_OK = """
    import threading

    class Former:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)

        def run(self):
            with self._cond:
                self._cond.wait(timeout=1.0)
"""


def test_condition_wait_under_its_lock_is_not_blocking(tmp_path):
    """wait() releases the lock while waiting — the one correct way to
    block under a lock must stay clean (the facade coalescer shape)."""
    rep = _vet(tmp_path, "m.py", COND_WAIT_OK)
    assert [f for f in rep.findings
            if f.rule == "lock-blocking-call"] == []


# -- waiver grammar ----------------------------------------------------------

WAIVED = """
    import threading
    import time

    L = threading.Lock()

    def tick():
        with L:
            time.sleep(0.5)  # vet: ignore[lock-blocking-call] bounded test stall, lock is test-private
"""

WAIVED_BARE = """
    import threading
    import time

    L = threading.Lock()

    def tick():
        with L:
            time.sleep(0.5)  # vet: ignore[lock-blocking-call]
"""


def test_lock_waiver_with_justification_suppresses(tmp_path):
    rep = _vet(tmp_path, "m.py", WAIVED)
    assert [f for f in rep.findings
            if f.rule == "lock-blocking-call"] == []
    assert any(w.rule == "lock-blocking-call" for w in rep.waivers)


def test_lock_waiver_without_justification_is_a_finding(tmp_path):
    rep = _vet(tmp_path, "m.py", WAIVED_BARE)
    assert any(f.rule == "waiver-syntax" for f in rep.findings)
    assert any(f.rule == "lock-blocking-call" for f in rep.findings)


# -- runtime: ownership enforcement ------------------------------------------

def test_require_held_catches_cross_thread_unguarded_mutation(armed):
    """The scripted race: a worker mutates guarded state without taking
    the owning lock — require_held (the runtime teeth behind the
    guarded-by annotation) raises InvariantViolation."""
    lock = locks.VetLock("t.guarded")
    state = {"n": 0}
    errors = []

    def mutate_unguarded():
        try:
            lock.require_held("t.state")
            state["n"] += 1
        except guards.InvariantViolation as e:
            errors.append(str(e))

    t = threading.Thread(target=mutate_unguarded)
    t.start()
    t.join()
    assert errors and "t.guarded" in errors[0]
    assert state["n"] == 0
    # the guarded path is clean
    with lock:
        lock.require_held("t.state")
        state["n"] += 1
    assert state["n"] == 1


def test_require_held_rejects_wrong_thread_even_while_held(armed):
    """Holding the lock on thread A does not license thread B."""
    lock = locks.VetLock("t.wrongthread")
    entered = threading.Event()
    release = threading.Event()
    errors = []

    def holder():
        with lock:
            entered.set()
            release.wait(timeout=5)

    def intruder():
        entered.wait(timeout=5)
        try:
            lock.require_held("t.state")
        except guards.InvariantViolation as e:
            errors.append(str(e))
        finally:
            release.set()

    th, ti = (threading.Thread(target=holder),
              threading.Thread(target=intruder))
    th.start(); ti.start()
    th.join(timeout=5); ti.join(timeout=5)
    assert errors, "intruder thread must not satisfy require_held"


def test_owner_thread_contract(armed):
    owner = locks.OwnerThread("t.plane")
    owner.check("cycle()")  # first toucher wins
    failures = []

    def other():
        try:
            owner.check("cycle()")
        except guards.InvariantViolation as e:
            failures.append(str(e))

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert failures and "single-threaded by contract" in failures[0]
    owner.reset()  # hand-off: next toucher owns
    t = threading.Thread(target=owner.check)
    t.start()
    t.join()


# -- runtime: order inversions -----------------------------------------------

def test_runtime_detector_counts_ab_ba_inversion(armed):
    a = locks.VetLock("t.inv.A")
    b = locks.VetLock("t.inv.B")
    inv0 = locks._INVERSIONS.total()  # noqa: SLF001
    with a:
        with b:
            pass

    def reversed_order():
        with b:
            with a:
                pass

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join()
    assert locks._INVERSIONS.total() - inv0 == 1  # noqa: SLF001
    recent = locks.state_payload()["inversions"]["recent"]
    assert recent and recent[-1]["pair"] == "t.inv.A|t.inv.B"


def test_runtime_detector_consistent_order_counts_nothing(armed):
    a = locks.VetLock("t.ok.A")
    b = locks.VetLock("t.ok.B")
    inv0 = locks._INVERSIONS.total()  # noqa: SLF001
    for _ in range(3):
        with a:
            with b:
                pass
    assert locks._INVERSIONS.total() - inv0 == 0  # noqa: SLF001


# -- runtime: deadlock watchdog ----------------------------------------------

def test_watchdog_trips_once_per_injected_stall(armed):
    now = [100.0]
    locks.set_clock(lambda: now[0])
    try:
        wd = locks.LockWatchdog(threshold_s=5.0)
        stalled = locks.VetLock("t.stall")
        trips0 = locks._TRIPS.total()  # noqa: SLF001
        stalled.acquire()
        try:
            assert wd.check() == []  # young hold: quiet
            now[0] = 106.0  # inject the stall
            trips = wd.check()
            assert [t["lock"] for t in trips] == ["t.stall"]
            assert trips[0]["held_s"] == pytest.approx(6.0)
            assert wd.check() == []  # once per hold, not per poll
        finally:
            stalled.release()
        now[0] = 120.0
        assert wd.check() == []  # released: nothing to trip
        assert locks._TRIPS.total() - trips0 == 1  # noqa: SLF001
    finally:
        locks.set_clock()


# -- runtime: disarmed path is free ------------------------------------------

def test_disarmed_lock_is_zero_overhead():
    assert not guards.armed()
    fam_before = set(REGISTRY.snapshot())
    locks.reset_for_tests()
    lock = locks.VetLock("t.disarmed")
    hold0 = locks._HOLD.count(lock="t.disarmed")  # noqa: SLF001
    for _ in range(100):
        with lock:
            pass
    # no bookkeeping ran: no ownership, no hold observations, no edges
    assert lock._owner is None  # noqa: SLF001
    assert lock._acquired_at is None  # noqa: SLF001
    assert locks._HOLD.count(lock="t.disarmed") == hold0  # noqa: SLF001
    assert locks.state_payload()["order_edges"] == 0
    # and no new metric families appeared (all three karmada_lock_*
    # families register at import, before any traffic)
    assert set(REGISTRY.snapshot()) == fam_before


def test_state_payload_shape(armed):
    lock = locks.VetLock("t.payload")
    with lock:
        payload = locks.state_payload()
        row = next(r for r in payload["locks"]
                   if r["name"] == "t.payload")
        assert row["owner"] == threading.current_thread().name
        assert row["held_for_s"] is not None
    assert payload["armed"] is True
    assert {"locks", "owner_threads", "order_edges", "inversions",
            "watchdog"} <= set(payload)


# -- CLI: --format github ----------------------------------------------------

def test_vet_format_github_emits_error_annotations(tmp_path, capsys):
    from karmada_tpu import cli

    (tmp_path / "m.py").write_text(textwrap.dedent(CYCLE_BAD))
    rc = cli.main(["vet", str(tmp_path), "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 1
    line = next(ln for ln in out.splitlines() if ln.startswith("::error "))
    assert "file=" in line and "line=" in line \
        and "title=vet lock-order::" in line

    (tmp_path / "m.py").write_text(textwrap.dedent(CYCLE_FIXED))
    rc = cli.main(["vet", str(tmp_path), "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "::error" not in out
