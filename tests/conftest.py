"""Test harness: force an 8-virtual-device CPU platform before jax inits.

Multi-chip TPU hardware is unavailable in CI; all sharding tests run against
a virtual 8-device CPU mesh (the driver separately dry-runs the multi-chip
path via __graft_entry__.dryrun_multichip).  The actual pinning logic —
including dropping tunnel-backed accelerator backend factories that would
otherwise hang jax.devices() — lives in karmada_tpu/utils/jaxenv.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from karmada_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(8)
