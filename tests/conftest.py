"""Test harness: force an 8-virtual-device CPU platform before jax imports.

Multi-chip TPU hardware is unavailable in CI; all sharding tests run against
a virtual 8-device CPU mesh (the driver separately dry-runs the multi-chip
path via __graft_entry__.dryrun_multichip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Drop any tunnel-backed accelerator plugin (e.g. the axon TPU proxy) so the
# suite never blocks on remote tunnel health: backends() would otherwise
# initialise every registered factory even under JAX_PLATFORMS=cpu.
try:
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "interpreter"):
            _xb._backend_factories.pop(_name, None)
    # a tunnel sitecustomize may have imported jax before this file ran,
    # freezing jax_platforms from the outer environment
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - best effort
    pass
