"""End-to-end slice: template+policy -> schedule -> Work -> member -> status.

Exercises the reference call stacks 3.1-3.4 (SURVEY.md section 3) entirely
in-process: detector matching, batched/serial scheduling, Work rendering
with overrides, member apply, and status reflection back to the template.
"""

import os

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import deep_get
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    ClusterAffinity,
    ClusterPreferences,
    ImageOverrider,
    ObjectMeta,
    OverridePolicy,
    Overriders,
    OverrideSpec,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
    RuleWithCluster,
)
from karmada_tpu.models.work import COND_SCHEDULED, ResourceBinding, Work


def nginx(replicas=6, cpu="500m"):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "nginx", "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "template": {"spec": {"containers": [
                {"name": "nginx", "image": "nginx:1.19",
                 "resources": {"requests": {"cpu": cpu, "memory": "1Gi"}}},
            ]}},
        },
    }


def policy(name="nginx-pp", divided=True, clusters=None):
    if divided:
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        )
    else:
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    placement = Placement(replica_scheduling=rs)
    if clusters:
        placement.cluster_affinity = ClusterAffinity(cluster_names=clusters)
    return PropagationPolicy(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=placement,
        ),
    )


@pytest.fixture
def cp():
    plane = ControlPlane(backend="serial")
    plane.add_member("m1", cpu_milli=64_000)
    plane.add_member("m2", cpu_milli=32_000)
    plane.add_member("m3", cpu_milli=16_000)
    plane.tick()
    return plane


def test_full_propagation_loop(cp):
    cp.apply_policy(policy())
    cp.apply(nginx(replicas=6))
    cp.tick()

    # 3.1 detector: binding exists with interpreted replicas/requirements
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert rb.spec.replicas == 6
    assert rb.spec.replica_requirements.resource_request["cpu"].milli == 500

    # 3.2 scheduler: all replicas divided across the fleet
    assert sum(tc.replicas for tc in rb.spec.clusters) == 6
    assert any(c.type == COND_SCHEDULED and c.status == "True"
               for c in rb.status.conditions)

    # 3.3 works rendered + applied to members with revised replicas
    total_member_replicas = 0
    for tc in rb.spec.clusters:
        from karmada_tpu.controllers.binding import work_name

        w = cp.store.get(Work.KIND, f"karmada-es-{tc.name}", work_name(rb))
        manifest = w.spec.workload[0]
        assert manifest["spec"]["replicas"] == tc.replicas
        applied = cp.member(tc.name).get("Deployment", "default", "nginx")
        assert applied is not None
        total_member_replicas += applied.manifest["spec"]["replicas"]
    assert total_member_replicas == 6

    # 3.4 status reflected: template aggregates member statuses
    cp.tick()
    template = cp.store.get("Deployment", "default", "nginx")
    assert template.manifest["status"]["readyReplicas"] == 6


def test_scale_up_keeps_existing_assignment(cp):
    cp.apply_policy(policy())
    cp.apply(nginx(replicas=6))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    first = {tc.name: tc.replicas for tc in rb.spec.clusters}

    cp.apply(nginx(replicas=12))
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    second = {tc.name: tc.replicas for tc in rb.spec.clusters}
    assert sum(second.values()) == 12
    for name, n in first.items():  # steady mode: no disruption
        assert second.get(name, 0) >= n


def test_duplicated_propagates_full_replicas(cp):
    cp.apply_policy(policy(divided=False, clusters=["m1", "m2"]))
    cp.apply(nginx(replicas=4))
    cp.tick()
    for m in ("m1", "m2"):
        applied = cp.member(m).get("Deployment", "default", "nginx")
        assert applied.manifest["spec"]["replicas"] == 4
    assert cp.member("m3").get("Deployment", "default", "nginx") is None


def test_override_policy_rewrites_image(cp):
    cp.apply_policy(policy(divided=False, clusters=["m1"]))
    op = OverridePolicy(
        metadata=ObjectMeta(name="img", namespace="default"),
        spec=OverrideSpec(
            resource_selectors=[ResourceSelector(kind="Deployment")],
            override_rules=[RuleWithCluster(
                target_cluster=ClusterAffinity(cluster_names=["m1"]),
                overriders=Overriders(image_overrider=[
                    ImageOverrider(component="Registry", operator="replace",
                                   value="registry.local")]),
            )],
        ),
    )
    cp.apply_policy(op)
    cp.apply(nginx())
    cp.tick()
    applied = cp.member("m1").get("Deployment", "default", "nginx")
    image = deep_get(applied.manifest, "spec.template.spec")["containers"][0]["image"]
    assert image == "registry.local/nginx:1.19"


def test_template_delete_cleans_up(cp):
    cp.apply_policy(policy())
    cp.apply(nginx())
    cp.tick()
    assert cp.member("m1").get("Deployment", "default", "nginx") is not None \
        or cp.member("m2").get("Deployment", "default", "nginx") is not None

    cp.delete("Deployment", "default", "nginx")
    cp.tick()
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is None
    assert len(cp.store.list(Work.KIND)) == 0
    for m in ("m1", "m2", "m3"):
        assert cp.member(m).get("Deployment", "default", "nginx") is None


def test_policy_delete_cleans_bindings(cp):
    cp.apply_policy(policy())
    cp.apply(nginx())
    cp.tick()
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is not None
    cp.delete(PropagationPolicy.KIND, "default", "nginx-pp")
    cp.tick()
    assert cp.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is None


def test_member_object_recreated_when_deleted(cp):
    cp.apply_policy(policy(divided=False, clusters=["m1"]))
    cp.apply(nginx(replicas=2))
    cp.tick()
    assert cp.member("m1").get("Deployment", "default", "nginx") is not None
    # someone deletes the workload inside the member cluster
    cp.member("m1").delete("Deployment", "default", "nginx")
    cp.tick()
    assert cp.member("m1").get("Deployment", "default", "nginx") is not None


def test_device_backend_end_to_end():
    plane = ControlPlane(backend="device")
    plane.add_member("m1", cpu_milli=64_000)
    plane.add_member("m2", cpu_milli=32_000)
    plane.tick()
    plane.apply_policy(policy())
    plane.apply(nginx(replicas=8))
    plane.tick()
    rb = plane.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert sum(tc.replicas for tc in rb.spec.clusters) == 8
    for tc in rb.spec.clusters:
        applied = plane.member(tc.name).get("Deployment", "default", "nginx")
        assert applied.manifest["spec"]["replicas"] == tc.replicas


def test_native_backend_schedules_like_serial():
    """backend="native": the C++ pipeline drives real scheduling decisions
    with serial fallback for its unsupported classes."""
    from karmada_tpu import native as native_mod

    if not native_mod.available():
        pytest.skip(f"native unavailable: {native_mod.build_error()}")

    results = {}
    for backend in ("serial", "native"):
        cp = ControlPlane(backend=backend)
        cp.add_member("m1", cpu_milli=64_000)
        cp.add_member("m2", cpu_milli=32_000)
        cp.apply(nginx(replicas=6))
        cp.apply_policy(policy())
        cp.tick()
        rb = cp.store.get("ResourceBinding", "default", "nginx-deployment")
        results[backend] = {tc.name: tc.replicas for tc in rb.spec.clusters}
        assert sum(results[backend].values()) == 6, backend
    assert results["native"] == results["serial"]


def test_native_backend_affinity_failover_loop():
    """ClusterAffinities multi-term failover under backend="native": the
    first term has no feasible cluster, the scheduler must fail over to
    the second term (snapshot reused across rounds)."""
    from karmada_tpu import native as native_mod

    if not native_mod.available():
        pytest.skip(f"native unavailable: {native_mod.build_error()}")
    from karmada_tpu.models.policy import ClusterAffinityTerm

    cp = ControlPlane(backend="native")
    cp.add_member("m1")
    cp.add_member("m2")
    manifest = nginx(replicas=4)
    cp.apply(manifest)
    pol = policy()
    pol.spec.placement.cluster_affinity = None
    pol.spec.placement.cluster_affinities = [
        ClusterAffinityTerm(affinity_name="primary", affinity=ClusterAffinity(
            cluster_names=["absent-a", "absent-b"])),
        ClusterAffinityTerm(affinity_name="backup", affinity=ClusterAffinity(
            cluster_names=["m1", "m2"])),
    ]
    cp.apply_policy(pol)
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert sum(tc.replicas for tc in rb.spec.clusters) == 4
    assert {tc.name for tc in rb.spec.clusters} <= {"m1", "m2"}
    assert rb.status.scheduler_observed_affinity_name == "backup"


@pytest.mark.skipif(os.environ.get("KARMADA_TPU_SOAK") != "1",
                    reason="600-member fleet e2e is opt-in (slow)")
def test_big_tier_through_scheduler_service():
    """ROUTE_DEVICE_BIG end to end through the scheduler SERVICE at a
    fleet large enough to engage the compact tiers (C > 528): a
    150-replica workload and a 200-cluster spread canary both schedule on
    the device path."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.meta import ObjectMeta
    from karmada_tpu.models.policy import (
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        REPLICA_DIVISION_AGGREGATED,
        REPLICA_DIVISION_WEIGHTED,
        REPLICA_SCHEDULING_DIVIDED,
        SPREAD_BY_FIELD_CLUSTER,
        ClusterPreferences,
        Placement,
        PropagationPolicy,
        PropagationSpec,
        ReplicaSchedulingStrategy,
        ResourceSelector,
        SpreadConstraint,
    )
    from karmada_tpu.models.work import ResourceBinding

    cp = ControlPlane(backend="device")
    for i in range(600):
        cp.add_member(f"m{i:03d}", cpu_milli=16_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="wide", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name="huge")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))))))
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="canary", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment", name="probe")],
            placement=Placement(
                spread_constraints=[SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                    min_groups=150, max_groups=200)],
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                    replica_division_preference=REPLICA_DIVISION_AGGREGATED)))))
    for name, reps in (("huge", 150), ("probe", 40)):
        cp.apply({"apiVersion": "apps/v1", "kind": "Deployment",
                  "metadata": {"name": name, "namespace": "default"},
                  "spec": {"replicas": reps,
                           "template": {"spec": {"containers": [
                               {"name": "c", "resources": {
                                   "requests": {"cpu": "50m"}}}]}}}})
    cp.tick()
    for name, reps in (("huge", 150), ("probe", 40)):
        rb = cp.store.get(ResourceBinding.KIND, "default",
                          f"{name}-deployment")
        assert sum(t.replicas for t in rb.spec.clusters) == reps, name
