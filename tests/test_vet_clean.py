"""The standing tier-1 gate: `karmadactl vet` over karmada_tpu/ is clean.

Any finding the analyzer reports on the live tree fails this test — the
fix is to repair the code (or, for a deliberate exception, add a
`# vet: ignore[rule] <why>` waiver whose justification survives review).
Waivers are enumerated and must each carry a justification.
"""

import json
import os

from karmada_tpu.analysis.vet import run_vet

PKG = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "karmada_tpu"))


def test_vet_clean_over_package():
    report = run_vet([PKG])
    # sanity: the walk really covered the package, not an empty dir
    assert report.files > 50
    msgs = [f"{f.file}:{f.line} [{f.rule}] {f.message}"
            for f in report.findings]
    assert not msgs, "vet findings on the live tree:\n" + "\n".join(msgs)


def test_vet_waivers_enumerated_and_justified():
    report = run_vet([PKG])
    d = report.to_dict()
    assert d["clean"] is True
    assert d["counts"]["waivers"] == len(d["waivers"])
    for w in d["waivers"]:
        assert w["justification"].strip(), w
        assert w["rule"] in d["counts"]["by_rule"]
    # the JSON is machine-ingestible (bench/watch tooling contract)
    parsed = json.loads(report.to_json())
    assert parsed["version"] == 1
    assert set(parsed) == {"version", "clean", "files", "findings",
                           "waivers", "counts"}


def test_vet_covers_known_surfaces():
    """The passes must actually be LOOKING at the hot surfaces: the
    guarded-by annotations exist, the dtype table exists, and the jit
    roots are discovered (an empty analysis passing trivially would be a
    silent gate failure)."""
    from karmada_tpu.analysis import lock_discipline, trace_safety
    from karmada_tpu.analysis.core import collect_files
    from karmada_tpu.analysis.dtype_contract import harvest_tables

    files = collect_files([PKG])
    table = harvest_tables(files)
    assert "name_rank" in table and table["name_rank"] == "int64"
    assert "used_milli" in table  # carry contract harvested too
    annotated = [sf for sf in files
                 if lock_discipline._annotations(sf)]  # noqa: SLF001
    names = {os.path.basename(sf.path) for sf in annotated}
    assert {"recorder.py", "metrics.py", "deviceprobe.py", "worker.py",
            "service.py"} <= names
    solver = [sf for sf in files
              if sf.path.endswith(os.path.join("ops", "solver.py"))]
    mod = trace_safety._Module(solver[0])  # noqa: SLF001
    assert {"_schedule_core", "_schedule_compact_impl"} <= mod.roots()


def test_vet_covers_resident_plane():
    """The gate extends over karmada_tpu/resident/: the walk reaches the
    subsystem's files, and the spec-coverage pass harvests ResidentPlane's
    ndarray fields and judges every one against the meshing PartitionSpec
    table (or the declared RESIDENT_HOST_ONLY set) — the same drift
    detector that caught SolverBatch drift on day one.  A refactor that
    renames the class or moves the files would silently drop the new
    subsystem out of the gate; this pins it in."""
    from karmada_tpu.analysis import spec_coverage
    from karmada_tpu.analysis.core import collect_files

    files = collect_files([PKG])
    resident = {os.path.basename(sf.path) for sf in files
                if (os.sep + "resident" + os.sep) in sf.path}
    assert {"__init__.py", "state.py", "deltas.py"} <= resident

    harvested = {}
    host_only: set = set()
    keys: set = set()
    for sf in files:
        line, k = spec_coverage._spec_table(sf.tree)  # noqa: SLF001
        if k and not keys:
            keys = k
            host_only = spec_coverage._const_strings(  # noqa: SLF001
                sf.tree, "HOST_ONLY_FIELDS")
        for cls, exempt in spec_coverage.COVERED_CLASSES:
            _line, f = spec_coverage._ndarray_fields(  # noqa: SLF001
                sf.tree, cls)
            if f and cls not in harvested:
                harvested[cls] = (sf, f, spec_coverage._const_strings(  # noqa: SLF001
                    sf.tree, exempt))
    assert {"SolverBatch", "ResidentPlane"} <= set(harvested)
    sf, fields, extra = harvested["ResidentPlane"]
    assert sf.path.endswith(os.path.join("resident", "state.py"))
    assert len(fields) >= 30  # the full plane, not a stub match
    # the coverage property itself, asserted directly: every resident
    # ndarray field is spec'd or declared host-only
    assert fields <= keys | host_only | extra, \
        sorted(fields - keys - host_only - extra)


def test_vet_covers_incremental_plane():
    """The gate extends over the dirty-set incremental-solve modules:
    the walk must reach ops/dirty.py (the classification kernel) and
    scheduler/incremental.py (the solver), so their metric names stay
    inside the metric-docs pass, the kernel inside trace-safety, and
    both inside every other vet rule.  A rename or move would silently
    drop the subsystem out of the gate; this pins it in."""
    from karmada_tpu.analysis import trace_safety
    from karmada_tpu.analysis.core import collect_files

    files = collect_files([PKG])
    by_tail = {os.path.join(*sf.path.split(os.sep)[-2:]): sf
               for sf in files}
    assert os.path.join("ops", "dirty.py") in by_tail
    assert os.path.join("scheduler", "incremental.py") in by_tail
    # the jitted classification kernel is discovered as a trace root
    mod = trace_safety._Module(  # noqa: SLF001
        by_tail[os.path.join("ops", "dirty.py")])
    assert "_dirty_core" in mod.roots()


def test_vet_covers_lock_plane():
    """The gate runs the lock-order pass over the live tree and the pass
    actually SEES the serve-plane locks: the VetLock creation sites in
    the scheduler and facade resolve to lock definitions, and the
    deliberate lock-held estimator RPC sites in estimator/wire.py are
    present as APPLIED lock-blocking-call waivers (a waiver only lands
    in report.waivers when its finding was really produced — if the
    pass stopped running or stopped recognizing VetLock, this pins the
    regression)."""
    from karmada_tpu.analysis import lock_order
    from karmada_tpu.analysis.core import RULES, collect_files
    from karmada_tpu.analysis.vet import PASSES

    assert "lock-order" in RULES and "lock-blocking-call" in RULES
    assert "lock-order" in PASSES

    files = collect_files([PKG])
    by_tail = {os.path.join(*sf.path.split(os.sep)[-2:]): sf
               for sf in files}
    sched = lock_order._Mod(  # noqa: SLF001
        by_tail[os.path.join("scheduler", "service.py")])
    facade = lock_order._Mod(  # noqa: SLF001
        by_tail[os.path.join("facade", "service.py")])
    sched_locks = {a for t in sched.class_locks.values() for a in t}
    facade_locks = {a for t in facade.class_locks.values() for a in t}
    assert "_queue_lock" in sched_locks
    assert {"_lock", "_solve_lock"} <= facade_locks

    report = run_vet([PKG])
    wire_waivers = [w for w in report.waivers
                    if w.rule == "lock-blocking-call"
                    and w.file.endswith(os.path.join("estimator",
                                                     "wire.py"))]
    assert len(wire_waivers) == 6, \
        [(w.file, w.line) for w in report.waivers
         if w.rule == "lock-blocking-call"]


def test_vet_covers_facade_plane():
    """The gate extends over karmada_tpu/facade/: the analyzer walk must
    reach every module of the subsystem, so its metric names stay inside
    the metric-docs pass and its code inside every other vet rule.  A
    rename or package move would silently drop the facade out of the
    gate; this pins it in (the resident-plane test's shape)."""
    from karmada_tpu.analysis.core import collect_files

    files = collect_files([PKG])
    facade = {os.path.basename(sf.path) for sf in files
              if (os.sep + "facade" + os.sep) in sf.path}
    assert {"__init__.py", "client.py", "messages.py", "metrics.py",
            "service.py", "whatif.py"} <= facade
