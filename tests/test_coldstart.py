"""AOT executable plane (ops/aotcache): cache keying, warm ledger,
persistent-cache hit/miss accounting, and the cold-start contract.

The compressed variant runs in tier-1: one process, a tmp cache dir, two
AOT warms of the same executables — the second must be served entirely
from the persistent cache (hits, zero new misses).  The honest
two-process variant (fresh interpreter per run, the COLDSTART_r*.json
contract) is marked slow.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys

import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.ops import aotcache

pytestmark = pytest.mark.coldstart


@pytest.fixture
def armed_cache(tmp_path):
    """Persistent cache armed at a tmp dir; disarmed after the test so
    later tests keep their compile behavior."""
    info = aotcache.enable(str(tmp_path / "cache"), min_compile_time_s=0.0)
    yield info
    aotcache.disable_for_tests()


def test_cache_key_components():
    import jax

    key = aotcache.cache_key("cpu")
    assert aotcache.machine_tag() in key
    assert f"jax{jax.__version__}" in key
    assert "mesh" not in key
    assert aotcache.cache_key("cpu", (2, 4)).endswith("-mesh2x4")
    assert aotcache.cache_key("accel").startswith("accel-shared")
    # accelerator executables target the chip: host features must NOT key
    assert aotcache.machine_tag() not in aotcache.cache_key("accel")


def test_variants_match_dispatchable_set():
    assert aotcache.variants_for(0.0, False) == ("plain",)
    assert aotcache.variants_for(0.5, False) == ("plain", "explain")
    assert aotcache.variants_for(0.0, True) == ("plain", "carry", "donated")
    assert set(aotcache.variants_for(1.0, True)) == set(aotcache.ALL_VARIANTS)


def test_warm_shapes_pow2_buckets():
    assert aotcache.warm_shapes(64, 1024) == (8, 16, 32, 64)
    assert aotcache.warm_shapes(4096, 256) == (8, 16, 32, 64, 128, 256)
    assert aotcache.warm_shapes(2, 2) == (8,)
    # a non-pow2 chunk cap still warms the CEILING bucket full chunks
    # pad into (B=1024 for pipeline_chunk=1000), not just the floor
    assert aotcache.warm_shapes(4096, 1000)[-1] == 1024


def test_warm_then_rewarm_zero_misses(armed_cache):
    """Compressed cold-start: the SAME executables warmed twice against
    one persistent cache — the second pass must be all hits, no misses
    (what makes a second PROCESS's warmup cheap)."""
    import jax

    rng = random.Random(0)
    clusters = bench.build_fleet(rng, 16)
    est = GeneralEstimator()
    # earlier tests in the suite may have compiled these very signatures
    # into jax's in-memory caches (which are consulted BEFORE the
    # persistent cache): drop them so the first warm genuinely compiles
    jax.clear_caches()
    h0, m0 = aotcache.counters()
    res1 = aotcache.warm_executables(clusters, est, shapes=(8,),
                                     variants=("plain", "carry"))
    h1, m1 = aotcache.counters()
    assert res1["_totals"]["compiled"] == 2
    assert m1 - m0 >= 2, "first warm must actually compile (cache misses)"
    ledger = aotcache.state_payload()["warmup"]
    assert {k: v["state"] for k, v in ledger.items()} == {
        "B8xC16:plain": "done", "B8xC16:carry": "done"}
    # second warm AFTER dropping jax's in-memory caches (what a fresh
    # process starts without): every XLA compile must be served from disk
    import jax

    jax.clear_caches()
    aotcache._STATE["warmup"] = {}  # noqa: SLF001 — fresh ledger for the re-warm
    res2 = aotcache.warm_executables(clusters, est, shapes=(8,),
                                     variants=("plain", "carry"))
    h2, m2 = aotcache.counters()
    assert res2["_totals"]["compiled"] == 2
    assert m2 - m1 == 0, "re-warm must not miss the persistent cache"
    assert h2 - h1 >= 2, "re-warm must be served by the persistent cache"


def test_warm_dedupes_pow2_aliases(armed_cache):
    """Sizes that pad to one pow2 bucket compile once."""
    rng = random.Random(1)
    clusters = bench.build_fleet(rng, 12)
    res = aotcache.warm_executables(clusters, GeneralEstimator(),
                                    shapes=(2, 5), variants=("plain",))
    assert res["_totals"]["compiled"] == 1
    assert res["B8xC16:plain"] == "already-warm" or any(
        v == "already-warm" for v in res.values())


def test_state_payload_in_debug_state(armed_cache):
    from karmada_tpu.utils.httpserve import ObservabilityServer

    state = ObservabilityServer(store=None)._state()  # noqa: SLF001
    assert state["aot"]["armed"] is True
    assert state["aot"]["cache_dir"] == armed_cache["cache_dir"]
    assert "hits" in state["aot"] and "misses" in state["aot"]


def test_disarmed_payload():
    aotcache.disable_for_tests()
    p = aotcache.state_payload()
    assert p["armed"] is False and p["cache_dir"] is None


def test_warm_device_path_covers_dispatchable_variants(monkeypatch):
    """Satellite fix: warm_device_path must warm the variant set the
    pipeline can actually dispatch, not just the plain pow2 shapes."""
    from karmada_tpu.loadgen import (
        ServeSlice, ServiceModel, VirtualClock, get_scenario,
        warm_device_path,
    )

    calls = []
    monkeypatch.setattr(
        aotcache, "warm_executables",
        lambda clusters, est, **kw: calls.append(kw) or {"_totals": {}})
    scenario = get_scenario("steady")
    plane = ServeSlice(scenario, VirtualClock(), ServiceModel(),
                       backend="serial", explain=0.25)
    # explain armed + multi-chunk cycles possible -> explain/carry/donated
    plane.scheduler.pipeline_chunk = scenario.batch_window // 2
    warm_device_path(plane, sizes=(2, 9))
    assert len(calls) == 1
    assert set(calls[0]["variants"]) == {"explain", "carry", "donated"}
    assert calls[0]["shapes"] == (2, 9)
    # plain-only configuration: no AOT pass at all
    calls.clear()
    plane2 = ServeSlice(scenario, VirtualClock(), ServiceModel(),
                        backend="serial")
    warm_device_path(plane2, sizes=(2,))
    assert not calls


def test_zero_copy_d2h_view():
    """finalize_compact's host views: on the CPU platform the COO planes
    arrive as read-only dlpack views, not copies."""
    import jax.numpy as jnp

    from karmada_tpu.ops import solver

    before = solver.D2H_ZEROCOPY.value()
    arr = jnp.arange(16, dtype=jnp.int32) * 2
    view = solver._host_view(arr)  # noqa: SLF001
    assert view.dtype == "int32" and view[3] == 6
    assert not view.flags.writeable, "dlpack view must be read-only"
    assert solver.D2H_ZEROCOPY.value() == before + 1
    import numpy as np

    plain = np.arange(4)
    assert solver._host_view(plain) is plain  # noqa: SLF001 — numpy passthrough


@pytest.mark.slow
def test_two_process_coldstart(tmp_path):
    """The COLDSTART_r*.json contract end to end: two fresh processes
    share one cache dir; the second must report ZERO compile-cache misses
    for the warmed shapes and a much cheaper warmup."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--coldstart",
         "--clusters", "64", "--coldstart-clusters", "24",
         "--coldstart-shapes", "8", "--ckpt-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=1500,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    line = [ln for ln in (r.stdout or "").splitlines()
            if ln.startswith("{")][-1]
    payload = json.loads(line)["detail"]["coldstart"]
    assert payload["second_misses"] == 0
    assert payload["second"]["hits"] >= 4
    assert payload["warm_ratio"] < 1.0
    assert payload["compile_warm_ratio"] < 0.5, (
        "persistent cache did not shrink the compile share")
    assert payload["decode"]["decode_parity_bit_exact"] is True
    # bench's OWN gate (<10% compile share) needs real-scale compiles to
    # dominate deserialization — COLDSTART_r01.json holds it at full
    # scale; at this toy scale only the payload contract is asserted
    assert r.returncode in (0, 1)
