"""Serve-path flight-recorder smoke (tier-1, JAX_PLATFORMS=cpu, no
device): a 2-cycle control plane with the trace buffer armed must expose
well-formed traces covering every pipeline stage over /debug/traces, the
`karmadactl trace` subcommand must fetch and render them, and every
metric/span name in the registry must be unique."""

import json
import re
import urllib.error
import urllib.request

import pytest

from karmada_tpu import obs
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.utils.httpserve import ObservabilityServer


def fetch(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def deployment(name, replicas=2):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicas": replicas,
                     "template": {"spec": {"containers": [
                         {"name": "a", "resources": {
                             "requests": {"cpu": "100m"}}}]}}}}


@pytest.fixture
def traced_plane():
    """A device-backend plane with --trace-buffer semantics armed and a
    chunk size that forces the pipelined executor to split the cycle."""
    # ring sized so the cycle traces outlive the flood of tiny
    # reconcile traces each tick emits (eviction is the slow
    # shelf's job, but this test reads the ring)
    rec = obs.TRACER.configure(capacity=2048, slow_keep=8)
    try:
        cp = ControlPlane(backend="device", pipeline_chunk=2)
        cp.add_member("m1", cpu_milli=64_000)
        cp.add_member("m2", cpu_milli=64_000)
        cp.tick()
        cp.apply_policy(PropagationPolicy(
            metadata=ObjectMeta(name="pp", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                     kind="Deployment")],
                placement=Placement(),
            ),
        ))
        # cycle 1: five bindings through pipeline_chunk=2 -> 3 chunks w/carry
        for i in range(5):
            cp.apply(deployment(f"app-{i}"))
        cp.tick()
        # cycle 2: two more bindings (the "2-cycle serve")
        for i in range(5, 7):
            cp.apply(deployment(f"app-{i}"))
        cp.tick()
        for i in range(7):
            rb = cp.store.get("ResourceBinding", "default",
                              f"app-{i}-deployment")
            assert rb.spec.clusters, f"app-{i} never scheduled"
        yield cp, rec
    finally:
        obs.TRACER.disable()


def _scheduler_traces(traces):
    return [t for t in traces
            if any(s["name"] == obs.SPAN_CYCLE for s in t["spans"])]


def test_serve_smoke_traces_cover_every_pipeline_stage(traced_plane):
    cp, rec = traced_plane
    srv = ObservabilityServer(store=cp.store)
    base = srv.start()
    try:
        status, body = fetch(base + "/debug/traces")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        cycles = _scheduler_traces(payload["traces"])
        assert len(cycles) >= 2, "expected >= 2 scheduler cycles recorded"
        # well-formed: unique span ids, resolvable parents, end >= start
        for tr in payload["traces"]:
            ids = [s["span_id"] for s in tr["spans"]]
            assert len(ids) == len(set(ids))
            for s in tr["spans"]:
                assert s["end_s"] >= s["start_s"] >= 0
                assert s["parent_id"] is None or s["parent_id"] in ids
        # the 5-binding cycle pipelined into chunks covering every stage,
        # with demonstrable overlap (chunk k+1 submitted inside chunk k)
        big = max(cycles, key=lambda t: len(t["spans"]))
        names = {s["name"] for s in big["spans"]}
        for stage in obs.PIPELINE_STAGE_SPANS:
            assert stage in names, f"stage {stage} missing from {names}"
        chunks = sorted((s for s in big["spans"]
                         if s["name"] == obs.SPAN_CHUNK),
                        key=lambda s: s["attrs"]["index"])
        assert len(chunks) >= 2
        assert chunks[1]["start_s"] < chunks[0]["end_s"], (
            "encode of chunk k+1 must overlap the in-flight chunk k")
        # reconcile roots carry the queue-dwell attribute (store/worker)
        dwells = [s["attrs"].get("queue_dwell_s")
                  for t in payload["traces"] for s in t["spans"]
                  if s["name"].startswith(obs.SPAN_RECONCILE_PREFIX)]
        assert dwells and any(d is not None and d >= 0 for d in dwells)

        # slow shelf is populated and retrieval-by-id round-trips
        status, body = fetch(base + "/debug/traces/slow")
        slow = json.loads(body)
        assert status == 200 and slow["summaries"], "slow shelf empty"
        tid = big["trace_id"]
        status, body = fetch(f"{base}/debug/traces/{tid}")
        assert status == 200 and obs.SPAN_CHUNK in body  # text waterfall
        status, body = fetch(f"{base}/debug/traces/{tid}?format=json")
        assert json.loads(body)["trace_id"] == tid
        with pytest.raises(urllib.error.HTTPError):
            fetch(base + "/debug/traces/nosuchtrace")

        # /debug/state folds in trace stats + the probe history section
        status, body = fetch(base + "/debug/state")
        state = json.loads(body)
        assert state["traces"]["recent"] >= 2
        assert state["traces"]["capacity"] == 2048
        assert "device_probe" in state
    finally:
        srv.stop()


def test_karmadactl_trace_lists_and_renders(traced_plane, capsys):
    from karmada_tpu import cli

    cp, rec = traced_plane
    srv = ObservabilityServer(store=cp.store)
    base = srv.start()
    try:
        assert cli.main(["trace", "--endpoint", base]) == 0
        out = capsys.readouterr().out
        assert "TRACE" in out and "DURATION_MS" in out
        tid = next(t["trace_id"] for t in rec.recent()
                   if any(s["name"] == obs.SPAN_CHUNK for s in t["spans"]))
        assert tid in out or cli.main(
            ["trace", "--endpoint", base, "--slow"]) == 0
        assert cli.main(["trace", "--endpoint", base, tid]) == 0
        water = capsys.readouterr().out
        assert obs.SPAN_CHUNK in water and "|" in water
    finally:
        srv.stop()


def test_trace_cli_reports_disabled_tracer():
    from karmada_tpu import cli

    assert not obs.TRACER.enabled
    srv = ObservabilityServer()
    base = srv.start()
    try:
        assert cli.main(["trace", "--endpoint", base]) == 1
    finally:
        srv.stop()


def test_unknown_debug_ids_return_json_404_bodies():
    """Regression: unknown trace/decision ids (and unknown /debug/*
    paths) must answer a well-formed JSON 404 body ({"error": ...}) —
    never an unhandled exception or an empty 500."""
    srv = ObservabilityServer()
    base = srv.start()
    try:
        for path in ("/debug/traces/nosuchtrace",
                     "/debug/explain/default/nosuchbinding",
                     "/debug/nosuchendpoint"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                fetch(base + path)
            assert ei.value.code == 404, path
            assert ei.value.headers.get("Content-Type") == "application/json"
            body = json.loads(ei.value.read().decode())
            assert body.get("error"), (path, body)
        # the disarmed explain ring polls clean, like the trace endpoints
        status, body = fetch(base + "/debug/explain")
        assert status == 200 and json.loads(body)["enabled"] is False
    finally:
        srv.stop()


def test_registry_collision_all_metric_and_span_names_unique():
    """Every REGISTRY-declared metric name across the package and every
    SPAN_* constant must be unique — a silent name collision would merge
    two unrelated series (Registry.register returns the existing object)
    or two unrelated waterfall rows."""
    import pathlib

    import karmada_tpu

    pkg = pathlib.Path(karmada_tpu.__file__).parent
    decl = re.compile(
        r'REGISTRY\.(?:counter|gauge|histogram)\(\s*"([^"]+)"')
    metric_names = []
    for path in sorted(pkg.rglob("*.py")):
        metric_names.extend(decl.findall(path.read_text()))
    assert metric_names, "scan found no metric declarations?"
    dupes = {n for n in metric_names if metric_names.count(n) > 1}
    assert not dupes, f"metric name(s) declared twice: {sorted(dupes)}"
    assert len(set(obs.SPAN_NAMES)) == len(obs.SPAN_NAMES)
    overlap = set(metric_names) & set(obs.SPAN_NAMES)
    assert not overlap, f"span/metric name collision: {sorted(overlap)}"
    # declared module objects are the canonical registry entries
    from karmada_tpu.scheduler import metrics as sm
    from karmada_tpu.utils import deviceprobe as dp
    from karmada_tpu.utils.metrics import REGISTRY, _Metric

    for mod in (sm, dp):
        for attr in vars(mod).values():
            if isinstance(attr, _Metric):
                assert REGISTRY._metrics[attr.name] is attr  # noqa: SLF001
