"""Hierarchical two-tier solve (ops/shortlist): candidate shortlisting
plus the dense solver over the per-chunk candidate-union sub-vocabulary.

The golden contract under test: whenever every binding's eligible lane
set (feasible clusters plus previous-assignment lanes) fits k, the
shortlisted chunk's placements are BIT-EXACT against the full dense
dispatch — and when it does not fit, the chunk widens k, then falls
back to the dense dispatch loudly (metric + ledger event), never with a
wrong placement.  Covered here:

  * parity fuzz across affinity/static/dynamic/aggregated strategies,
    prev assignments, and multi-chunk carry (consumption crosses the
    per-chunk cluster-lane remap through the keyed CarryState);
  * widen-and-retry, and every fallback reason
    (uncovered / mixed_routes / union_wide / fused / below_threshold);
  * explain-plane verdicts through the vocabulary remap;
  * the loadgen `megafleet` scenario compressed on the virtual clock
    (device backend end to end, zero fallbacks);
  * AOT warm coverage (aotcache VARIANT_SHORTLIST: tier-1 kernel +
    tier-2 sub-shape solver), Scheduler/ControlPlane plumbing, the
    /debug/state shortlist block, and seeded spec-coverage fixtures for
    the new drift class;
  * 2-device mesh parity (8-device marked slow).
"""

import random
import textwrap

import numpy as np
import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.policy import (
    ClusterAffinity,
    Placement,
    ReplicaSchedulingStrategy,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_DIVISION_WEIGHTED,
    ClusterPreferences,
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    SpreadConstraint,
    SPREAD_BY_FIELD_REGION,
    SPREAD_BY_FIELD_CLUSTER,
)
from karmada_tpu.models.work import TargetCluster
from karmada_tpu.ops import meshing, shortlist as sl, tensors
from karmada_tpu.scheduler import pipeline

pytestmark = pytest.mark.shortlist


@pytest.fixture(autouse=True)
def _no_mesh_leak():
    yield
    meshing.deactivate()


def _fleet(n, seed=0):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, n)
    return clusters, tensors.ClusterIndex.build(clusters)


def _affinity_placements(rng, names, n=12, lo=3, hi=16):
    """Device-routed strategy mix over affinity subsets (the shape whose
    eligible sets a small k covers): Duplicated, StaticWeight, and
    DynamicWeight-Divided, all restricted to [lo, hi] clusters."""
    out = []
    for j in range(n):
        k = rng.randint(lo, min(hi, len(names)))
        start = rng.randrange(len(names))
        picked = [names[(start + i) % len(names)] for i in range(k)]
        aff = ClusterAffinity(cluster_names=picked)
        if j % 3 == 0:
            rs = ReplicaSchedulingStrategy(
                replica_scheduling_type="Duplicated")
        elif j % 3 == 1:
            rs = ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED)
        else:
            rs = ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))
        out.append(Placement(cluster_affinity=aff, replica_scheduling=rs))
    return out


def _items(rng, n, placements, prev_of=None):
    items = bench.build_bindings(rng, n, placements)
    if prev_of:
        for b, targets in prev_of.items():
            items[b][0].clusters = [
                TargetCluster(name=nm, replicas=rep) for nm, rep in targets]
    return items


def _run(items, cindex, est, cfg, **kw):
    kw.setdefault("chunk", 64)
    kw.setdefault("waves", 4)
    kw.setdefault("carry", True)
    kw.setdefault("carry_spread", True)
    return pipeline.run_pipeline(items, cindex, est, shortlist=cfg, **kw)


def _assert_parity(dense, shortlisted):
    assert dense.results.keys() == shortlisted.results.keys()
    for i, want in dense.results.items():
        got = shortlisted.results[i]
        if isinstance(want, Exception):
            assert isinstance(got, type(want)), (i, want, got)
        else:
            assert not isinstance(got, Exception), (i, got)
            assert ({t.name: t.replicas for t in got}
                    == {t.name: t.replicas for t in want}), i


def _fallback_delta(fn, reason):
    before = sl.SHORTLIST_FALLBACKS.value(reason=reason)
    out = fn()
    return out, sl.SHORTLIST_FALLBACKS.value(reason=reason) - before


# -- parity ------------------------------------------------------------------


def test_parity_fuzz_covered_bit_exact():
    """Shortlist-vs-dense placements bit-exact whenever every eligible
    set fits k (fuzz over seeds / strategies / chunk boundaries)."""
    for seed in (3, 17):
        rng = random.Random(seed)
        clusters, cindex = _fleet(96, seed=seed)
        names = [c.metadata.name for c in clusters]
        pls = _affinity_placements(rng, names)
        items = _items(rng, 150, pls)
        est = GeneralEstimator()
        dense = _run(items, cindex, est, None)
        cfg = sl.ShortlistConfig(k=24, min_cells=0, union_frac=1.0)
        fb0 = sl.SHORTLIST_FALLBACKS.total()
        shortlisted = _run(items, cindex, est, cfg)
        assert sl.SHORTLIST_FALLBACKS.total() == fb0, "unexpected fallback"
        _assert_parity(dense, shortlisted)


def test_prev_assignment_lanes_ride_the_union():
    """Previous-assignment lanes are eligible even beyond the affinity
    row (scale-up/down read them): parity holds and the union contains
    every prev lane."""
    rng = random.Random(5)
    clusters, cindex = _fleet(64, seed=5)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=6)
    # prev targets deliberately outside each binding's affinity subset
    prev_of = {b: [(names[(b * 7 + 3) % len(names)], 2),
                   (names[(b * 11 + 9) % len(names)], 1)]
               for b in range(0, 40, 5)}
    items = _items(rng, 40, pls, prev_of=prev_of)
    est = GeneralEstimator()
    dense = _run(items, cindex, est, None)
    cfg = sl.ShortlistConfig(k=24, min_cells=0, union_frac=1.0)
    shortlisted = _run(items, cindex, est, cfg)
    _assert_parity(dense, shortlisted)
    # the sub-vocabulary covered the prev lanes (direct shrink check)
    batch = tensors.encode_batch(items, cindex, est)
    sub, info = sl.shrink_chunk(batch, cfg)
    assert sub is not None, info
    lanes = set(sub.sub_lanes[sub.sub_lanes >= 0].tolist())
    prev_np = np.asarray(batch.prev_idx)
    assert set(prev_np[prev_np >= 0].tolist()) <= lanes


def test_carry_across_shortlisted_chunks():
    """Multi-chunk contention: chunk k+1 prices against what chunks <=k
    consumed, ACROSS different per-chunk sub-vocabularies (the keyed
    CarryState renders accumulators through the lane remap).  A tight
    fleet makes the carry observable — dropping it would change
    placements."""
    rng = random.Random(29)
    clusters = bench.build_fleet(rng, 48)
    # shrink capacity so contention bites across chunks
    for c in clusters:
        c.status.resource_summary.allocatable["pods"] = (
            type(c.status.resource_summary.allocatable["pods"])
            .from_units(24))
    cindex = tensors.ClusterIndex.build(clusters)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=8, lo=4, hi=10)
    items = _items(rng, 180, pls)
    est = GeneralEstimator()
    dense = _run(items, cindex, est, None, chunk=48)
    cfg = sl.ShortlistConfig(k=16, min_cells=0, union_frac=1.0)
    shortlisted = _run(items, cindex, est, cfg, chunk=48)
    _assert_parity(dense, shortlisted)
    # the run really was multi-chunk and really was shortlisted
    assert shortlisted.chunks >= 3
    assert sl.state_payload()["last"]["fallback_reason"] is None


# -- widen + fallbacks -------------------------------------------------------


def test_widen_and_retry_then_exact():
    rng = random.Random(41)
    clusters, cindex = _fleet(64, seed=41)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=4, lo=12, hi=20)
    items = _items(rng, 30, pls)
    est = GeneralEstimator()
    batch = tensors.encode_batch(items, cindex, est)
    w0 = sl.SHORTLIST_WIDENINGS.value()
    cfg = sl.ShortlistConfig(k=4, k_max=64, min_cells=0, union_frac=1.0)
    sub, info = sl.shrink_chunk(batch, cfg)
    assert sub is not None, info
    assert info["widened"] >= 1 and info["k"] > 4
    assert sl.SHORTLIST_WIDENINGS.value() > w0
    dense = _run(items, cindex, est, None)
    shortlisted = _run(items, cindex, est, cfg)
    _assert_parity(dense, shortlisted)


def test_uncovered_fallback_is_loud_and_correct():
    """With truncation off, super-k_max rows still drag the chunk dense
    — loudly, with the offending BINDING KEYS named in the event."""
    from karmada_tpu.obs import events as ev

    rng = random.Random(43)
    clusters, cindex = _fleet(64, seed=43)
    names = [c.metadata.name for c in clusters]
    # 3 coverable placements + 1 whale: the whale rows NEED the
    # fallback, the coverable rows are merely dragged along
    pls = _affinity_placements(rng, names, n=3, lo=4, hi=6)
    pls += _affinity_placements(rng, names, n=1, lo=20, hi=24)
    items = _items(rng, 24, pls)
    est = GeneralEstimator()
    cfg = sl.ShortlistConfig(k=4, k_max=8, min_cells=0, union_frac=1.0,
                             truncate=False)
    batch = tensors.encode_batch(items, cindex, est)
    need0 = sl.SHORTLIST_FALLBACK_ROWS.value(kind="needed")
    drag0 = sl.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag")
    (sub, info), delta = _fallback_delta(
        lambda: sl.shrink_chunk(batch, cfg, part=items), "uncovered")
    assert sub is None and info["fallback"] == "uncovered"
    assert delta == 1
    # row-granular accounting: the offenders NEEDED the fallback, every
    # other valid row was merely dragged along by the chunk
    needed = sl.SHORTLIST_FALLBACK_ROWS.value(kind="needed") - need0
    dragged = sl.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag") - drag0
    assert needed >= 1 and dragged >= 1
    assert needed + dragged == 24
    recent = ev.state_payload(n=16)["recent"]
    fallback_msgs = [e.get("message", "") for e in recent
                     if e.get("reason") == ev.REASON_SHORTLIST_FALLBACK]
    assert fallback_msgs, recent
    # the widen-exhaustion message names the offending binding keys
    from karmada_tpu.obs import decisions as obs_decisions

    keys = {obs_decisions.default_key(spec) for spec, _st in items}
    assert any(any(k in m for k in keys) for m in fallback_msgs), \
        fallback_msgs
    # the pipeline still schedules correctly (dense fallback per chunk)
    dense = _run(items, cindex, est, None)
    shortlisted = _run(items, cindex, est, cfg)
    _assert_parity(dense, shortlisted)


def test_truncation_with_recall_bit_exact():
    """Truncation-with-recall (seeded): rows whose eligible set outgrows
    k_max leave the chunk as residual and re-solve per-binding at full
    width — one huge row no longer drags 24 rows dense, and placements
    stay bit-exact against the dense control (waves=1)."""
    from karmada_tpu.obs import events as ev

    rng = random.Random(43)
    clusters, cindex = _fleet(64, seed=43)
    names = [c.metadata.name for c in clusters]
    # 3 coverable rows + 1 seeded whale spanning most of the fleet
    pls = _affinity_placements(rng, names, n=3, lo=4, hi=6)
    pls += _affinity_placements(rng, names, n=1, lo=40, hi=48)
    items = _items(rng, 24, pls)
    est = GeneralEstimator()
    cfg = sl.ShortlistConfig(k=8, k_max=16, min_cells=0, union_frac=1.0)
    batch = tensors.encode_batch(items, cindex, est)
    need0 = sl.SHORTLIST_FALLBACK_ROWS.value(kind="needed")
    drag0 = sl.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag")
    fb0 = sl.SHORTLIST_FALLBACKS.total()
    sub, info = sl.shrink_chunk(batch, cfg, part=items)
    assert sub is not None, info
    residual = info["residual"]
    assert residual, "seeded whale row did not go residual"
    # the whale rows are placements index 3 mod 4
    assert all(i % 4 == 3 for i in residual), residual
    assert sl.SHORTLIST_FALLBACKS.total() == fb0, "no chunk fallback"
    assert (sl.SHORTLIST_FALLBACK_ROWS.value(kind="needed") - need0
            == len(residual))
    assert sl.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag") == drag0
    # residual rows' b_valid cleared in the sub-batch; others kept
    assert not any(bool(sub.b_valid[i]) for i in residual)
    # the truncation event names the offending binding keys
    recent = ev.state_payload(n=16)["recent"]
    trunc = [e for e in recent
             if e.get("reason") == ev.REASON_SHORTLIST_TRUNCATE]
    assert trunc, recent
    from karmada_tpu.obs import decisions as obs_decisions

    keys = {obs_decisions.default_key(items[i][0]) for i in residual}
    assert any(k in trunc[-1].get("message", "") for k in keys), trunc
    # end to end: bit-exact vs dense, through the pipeline's per-binding
    # residual finalize (exact only at waves=1)
    dense = _run(items, cindex, est, None, waves=1)
    shortlisted = _run(items, cindex, est, cfg, waves=1)
    _assert_parity(dense, shortlisted)


def test_truncation_disabled_at_waves_gt1():
    """waves>1 chunks may not truncate (rows see same-chunk consumption
    there): the pipeline passes allow_truncate=False and the chunk falls
    back dense instead — still correct."""
    rng = random.Random(43)
    clusters, cindex = _fleet(64, seed=43)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=3, lo=4, hi=6)
    pls += _affinity_placements(rng, names, n=1, lo=40, hi=48)
    items = _items(rng, 24, pls)
    est = GeneralEstimator()
    cfg = sl.ShortlistConfig(k=8, k_max=16, min_cells=0, union_frac=1.0)
    (dense, shortlisted), delta = _fallback_delta(
        lambda: (_run(items, cindex, est, None, waves=4),
                 _run(items, cindex, est, cfg, waves=4)), "uncovered")
    assert delta >= 1  # the whale forced the dense fallback, loudly
    _assert_parity(dense, shortlisted)


def test_mixed_routes_fallback():
    rng = random.Random(47)
    clusters, cindex = _fleet(64, seed=47)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=3)
    spread = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=2),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=1, max_groups=4),
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
    items = _items(rng, 20, pls + [spread])
    est = GeneralEstimator()
    batch = tensors.encode_batch(items, cindex, est)
    cfg = sl.ShortlistConfig(k=24, min_cells=0)
    (sub, info), delta = _fallback_delta(
        lambda: sl.shrink_chunk(batch, cfg), "mixed_routes")
    assert sub is None and info["fallback"] == "mixed_routes"
    assert delta == 1
    dense = _run(items, cindex, est, None)
    shortlisted = _run(items, cindex, est, cfg)
    _assert_parity(dense, shortlisted)


def test_union_wide_fallback():
    rng = random.Random(53)
    clusters, cindex = _fleet(64, seed=53)
    names = [c.metadata.name for c in clusters]
    # many groups jointly spanning most of the fleet
    pls = _affinity_placements(rng, names, n=16, lo=10, hi=16)
    items = _items(rng, 64, pls)
    est = GeneralEstimator()
    batch = tensors.encode_batch(items, cindex, est)
    cfg = sl.ShortlistConfig(k=16, min_cells=0, union_frac=0.2)
    (sub, info), delta = _fallback_delta(
        lambda: sl.shrink_chunk(batch, cfg), "union_wide")
    assert sub is None and info["fallback"] == "union_wide"
    assert delta == 1


def test_below_threshold_is_silent():
    rng = random.Random(59)
    clusters, cindex = _fleet(32, seed=59)
    items = _items(rng, 16, _affinity_placements(
        rng, [c.metadata.name for c in clusters], n=3))
    batch = tensors.encode_batch(items, cindex, GeneralEstimator())
    fb0 = sl.SHORTLIST_FALLBACKS.total()
    sub, info = sl.shrink_chunk(
        batch, sl.ShortlistConfig(k=8, min_cells=1 << 30))
    assert sub is None and info["fallback"] == "below_threshold"
    assert sl.SHORTLIST_FALLBACKS.total() == fb0


def test_fused_batch_falls_back():
    rng = random.Random(61)
    clusters, cindex = _fleet(32, seed=61)
    items = _items(rng, 16, _affinity_placements(
        rng, [c.metadata.name for c in clusters], n=3))
    batch = tensors.encode_batch(items, cindex, GeneralEstimator())
    batch.fused = True
    (sub, info), delta = _fallback_delta(
        lambda: sl.shrink_chunk(batch, sl.ShortlistConfig(
            k=8, min_cells=0)), "fused")
    assert sub is None and info["fallback"] == "fused"
    assert delta == 1


# -- explain through the remap ------------------------------------------------


def test_explain_verdicts_through_the_remap():
    from karmada_tpu.obs import decisions as obs_decisions

    rng = random.Random(67)
    clusters, cindex = _fleet(64, seed=67)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=6)
    items = _items(rng, 40, pls)
    est = GeneralEstimator()
    rec = obs_decisions.DecisionRecorder()
    cfg = sl.ShortlistConfig(k=24, min_cells=0, union_frac=1.0)
    res = _run(items, cindex, est, cfg, explain=rec)
    assert res.scheduled > 0
    recent = rec.recent()
    assert recent, "explain-armed shortlisted cycle recorded no decisions"
    union = set()
    batch = tensors.encode_batch(items, cindex, est)
    sub, _info = sl.shrink_chunk(batch, cfg)
    assert sub is not None
    union = set(sub.cluster_index.names)
    for d in recent:
        table = d.get("clusters") or []
        for row in table:
            assert row["name"] in union
    # parity against the dense explain run: same outcomes
    rec2 = obs_decisions.DecisionRecorder()
    dense = _run(items, cindex, est, None, explain=rec2)
    _assert_parity(dense, res)


# -- serve-path integration ---------------------------------------------------


@pytest.mark.soak
def test_megafleet_compressed_soak_zero_fallbacks():
    """The loadgen megafleet scenario end to end on the virtual clock:
    device backend, shortlist armed through the Scheduler, every chunk
    covered (zero fallbacks), everything scheduled."""
    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, ServiceModel, VirtualClock, get_scenario,
    )

    scenario = get_scenario("megafleet")
    assert scenario.shortlist_k > 0 and scenario.n_regions > 0
    clock = VirtualClock()
    model = ServiceModel()
    plane = ServeSlice(scenario, clock, model, backend="device")
    assert plane.scheduler.shortlist_k == scenario.shortlist_k
    disp0 = sl.SHORTLIST_DISPATCHES.value()
    fb0 = sl.SHORTLIST_FALLBACKS.total()
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=7)
    payload = driver.run()
    assert payload["injected"] > 0
    assert payload["scheduled"] == payload["injected"]
    assert sl.SHORTLIST_DISPATCHES.value() > disp0
    assert sl.SHORTLIST_FALLBACKS.total() == fb0


def test_scheduler_and_controlplane_plumbing():
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.scheduler.service import Scheduler
    from karmada_tpu.store.store import ObjectStore
    from karmada_tpu.store.worker import Runtime

    sched = Scheduler(ObjectStore(), Runtime(), backend="device",
                      shortlist_k=32, shortlist_min_cells=123)
    assert sched.shortlist_k == 32
    assert sched.shortlist_min_cells == 123
    # host backends never arm the tier (they build no SolverBatches)
    assert Scheduler(ObjectStore(), Runtime(), backend="serial",
                     shortlist_k=32).shortlist_k is None
    # the fused slot store composes: shrink reads the host masters via
    # the batch's fused_src handle and sub-gathers on device
    assert Scheduler(ObjectStore(), Runtime(), backend="device",
                     resident=True, resident_fused=True,
                     shortlist_k=32).shortlist_k == 32
    cp = ControlPlane(backend="device", shortlist_k=16)
    assert cp.scheduler.shortlist_k == 16


def test_debug_state_shortlist_block():
    from karmada_tpu.utils.httpserve import ObservabilityServer

    block = ObservabilityServer._shortlist_state()
    # this suite imported ops.shortlist, so the live payload shows —
    # and "active" tracks real dispatches, not module presence
    assert "dispatches" in block and "fallbacks" in block
    assert block["active"] == (block["dispatches"] > 0)


def test_armed_guards_accept_sub_batches():
    """serve --check-invariants must pass a shortlisted sub-batch at the
    solver entry (sub_lanes checked when present, the kernel's output
    fields skipped — they are never batch attributes)."""
    from karmada_tpu.analysis import guards

    rng = random.Random(89)
    clusters, cindex = _fleet(64, seed=89)
    items = _items(rng, 24, _affinity_placements(
        rng, [c.metadata.name for c in clusters], n=4))
    est = GeneralEstimator()
    batch = tensors.encode_batch(items, cindex, est)
    sub, info = sl.shrink_chunk(
        batch, sl.ShortlistConfig(k=24, min_cells=0, union_frac=1.0))
    assert sub is not None, info
    guards.check_batch(batch)  # dense: sub_lanes absent -> skipped
    guards.check_batch(sub)    # sub: lane map + gathered planes checked
    guards.arm()
    try:
        from karmada_tpu.ops.solver import solve_compact

        solve_compact(sub, waves=2)
    finally:
        guards.arm(False)


def test_state_payload_shape():
    p = sl.state_payload()
    for key in ("dispatches", "rows", "widenings", "fallbacks", "last"):
        assert key in p


# -- AOT warm coverage --------------------------------------------------------


def test_variants_for_shortlist():
    from karmada_tpu.ops import aotcache

    assert aotcache.variants_for(0.0, False) == ("plain",)
    assert aotcache.variants_for(0.0, False, shortlist=True) == \
        ("plain", "shortlist")
    assert "shortlist" in aotcache.variants_for(0.5, True, fused=True,
                                                shortlist=True)


def test_warm_executables_compiles_shortlist_pair():
    from karmada_tpu.ops import aotcache

    rng = random.Random(71)
    clusters = bench.build_fleet(rng, 24)
    label = "B8xC32:k8:shortlist"
    try:
        res = aotcache.warm_executables(
            clusters, GeneralEstimator(), shapes=(8,),
            variants=(aotcache.VARIANT_SHORTLIST,), shortlist_k=8)
        assert res["_totals"]["compiled"] == 1
        entry = res[label]
        assert entry["k"] == 8 and entry["compile_s"] >= 0
        # the tier-2 sub-shape solver warmed alongside the kernel
        assert "tier2" in entry and entry["tier2"]["compile_s"] >= 0
        ledger = aotcache.state_payload()["warmup"]
        assert ledger.get(label, {}).get("state") == "done"
    finally:
        aotcache._STATE["warmup"].pop(label, None)  # noqa: SLF001


# -- coarse aggregates + rebalance reuse -------------------------------------


def test_cycle_aggregates_memoized_per_cycle():
    rng = random.Random(73)
    clusters, cindex = _fleet(32, seed=73)
    items = _items(rng, 16, _affinity_placements(
        rng, [c.metadata.name for c in clusters], n=3))
    est = GeneralEstimator()
    cache = tensors.EncoderCache()
    b1 = tensors.encode_batch(items, cindex, est, cache=cache)
    b2 = tensors.encode_batch(items, cindex, est, cache=cache)
    a1 = sl.cycle_aggregates(b1)
    a2 = sl.cycle_aggregates(b2)
    assert a1 is a2  # same frozen cluster planes -> one aggregation
    # the memo pins its keyed sources: identity can never falsely hit
    assert a1["src"][0] is b1.avail_milli


def test_fleet_capacity_memo_and_rebalance_reuse():
    import copy

    rng = random.Random(79)
    clusters = bench.build_fleet(rng, 16)
    cap1 = sl.fleet_capacity(clusters)
    # the memo keys on (name, rv) — it must hit across DEEP COPIES (the
    # store's list() hands back fresh objects every call)
    cap2 = sl.fleet_capacity([copy.deepcopy(c) for c in clusters])
    assert np.array_equal(cap1, cap2)
    want = np.array(
        [int(c.status.resource_summary.allocatable["pods"].value())
         for c in clusters], np.int64)
    assert np.array_equal(cap1, want)
    # a churned cluster (rv bumped with a new summary) re-parses
    moved = copy.deepcopy(clusters[3])
    moved.metadata.resource_version += 1
    moved.status.resource_summary.allocatable["pods"] = (
        type(moved.status.resource_summary.allocatable["pods"])
        .from_units(7))
    cap3 = sl.fleet_capacity(clusters[:3] + [moved] + clusters[4:])
    assert cap3[3] == 7 and np.array_equal(cap3[:3], want[:3])
    # the rebalance plane's detect assembles through the same memo
    from karmada_tpu.rebalance.plane import RebalancePlane
    from karmada_tpu.store.store import ObjectStore

    plane = RebalancePlane(ObjectStore(), scheduler=None,
                           clock=lambda: 0.0)
    names, committed, capacity, valid, _by = plane._assemble(clusters, [])
    assert np.array_equal(capacity, want)


# -- vet drift fixtures (spec-coverage shortlist class) -----------------------


def _vet(tmp_path, files):
    from karmada_tpu.analysis.vet import run_vet

    for fname, src in files.items():
        (tmp_path / fname).write_text(textwrap.dedent(src))
    return run_vet([str(tmp_path)], rules=["spec-coverage"])


_MESHING_FIXTURE = """
    HOST_ONLY_FIELDS = frozenset({"route"})

    def shard_specs():
        return {"shortlist_idx": 1, "b_valid": 2}
"""


def test_vet_catches_unchained_shortlist_output(tmp_path):
    report = _vet(tmp_path, {
        "meshing.py": _MESHING_FIXTURE,
        "shortlist.py": """
            SHORTLIST_OUT_FIELDS = ("shortlist_idx", "mystery_plane")
            FIELD_DTYPES = {"shortlist_idx": "int32",
                            "mystery_plane": "int32"}
        """,
    })
    msgs = [f.message for f in report.findings]
    assert any("shortlist kernel output `mystery_plane`" in m
               and "shard_specs" in m for m in msgs), msgs


def test_vet_catches_untyped_shortlist_output(tmp_path):
    report = _vet(tmp_path, {
        "meshing.py": """
            HOST_ONLY_FIELDS = frozenset({"route"})

            def shard_specs():
                return {"shortlist_idx": 1, "shortlist_fcount": 2,
                        "b_valid": 3}
        """,
        "shortlist.py": """
            SHORTLIST_OUT_FIELDS = ("shortlist_idx", "shortlist_fcount")
            FIELD_DTYPES = {"shortlist_idx": "int32"}
        """,
    })
    msgs = [f.message for f in report.findings]
    assert any("shortlist kernel output `shortlist_fcount`" in m
               and "FIELD_DTYPES" in m for m in msgs), msgs


def test_vet_clean_on_real_tree_tables():
    keys = set(meshing.shard_specs())
    assert set(sl.SHORTLIST_OUT_FIELDS) <= keys
    assert set(sl.SHORTLIST_OUT_FIELDS) <= set(tensors.FIELD_DTYPES)
    assert set(sl.SHORTLIST_OUT_FIELDS) <= set(tensors.FIELD_AXES)
    assert "sub_lanes" in meshing.HOST_ONLY_FIELDS
    assert "sub_lanes" in tensors.FIELD_DTYPES


# -- mesh parity --------------------------------------------------------------


def _mesh_parity(shape, n_clusters=64, n_items=60):
    import jax

    rng = random.Random(83)
    clusters, cindex = _fleet(n_clusters, seed=83)
    names = [c.metadata.name for c in clusters]
    pls = _affinity_placements(rng, names, n=6)
    items = _items(rng, n_items, pls)
    est = GeneralEstimator()
    cfg = sl.ShortlistConfig(k=24, min_cells=0, union_frac=1.0)
    dense = _run(items, cindex, est, None, chunk=32)
    n_dev = shape[0] * shape[1]
    plan = meshing.activate(shape, devices=jax.devices()[:n_dev])
    assert plan is not None
    try:
        shortlisted = _run(items, cindex, est, cfg, chunk=32)
    finally:
        meshing.deactivate()
    _assert_parity(dense, shortlisted)


def test_mesh_2dev_parity():
    _mesh_parity((1, 2))


@pytest.mark.slow
def test_mesh_8dev_parity():
    _mesh_parity((2, 4))


@pytest.mark.slow
def test_parity_fuzz_heavy():
    for seed in range(8):
        rng = random.Random(100 + seed)
        clusters, cindex = _fleet(160, seed=seed)
        names = [c.metadata.name for c in clusters]
        pls = _affinity_placements(rng, names, n=16, lo=3, hi=28)
        prev_of = {b: [(names[(b * 13 + 1) % len(names)], 1 + b % 4)]
                   for b in range(0, 200, 7)}
        items = _items(rng, 200, pls, prev_of=prev_of)
        est = GeneralEstimator()
        dense = _run(items, cindex, est, None, chunk=48)
        shortlisted = _run(items, cindex, est,
                           sl.ShortlistConfig(k=32, min_cells=0, union_frac=1.0), chunk=48)
        _assert_parity(dense, shortlisted)
