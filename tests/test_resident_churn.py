"""Resident-state plane under churn (karmada_tpu/resident).

Two layers of the same property — the delta path re-encodes EXACTLY the
churned rows and the resident tensors stay bit-exact with a from-scratch
encode:

  * a direct unit property over ResidentState: per-cycle miss count ==
    churned-binding count, hit count == unchanged count, closing audit
    bit-exact (the tentpole's core contract);
  * the REAL loadgen `churn` scenario (compressed virtual time) driven
    through a device-backend ServeSlice with the resident plane armed: a
    spy derives each encode call's expected miss count from the pre-call
    cache state, so any spurious invalidation (re-encoding an unchanged
    row) or stale reuse (serving a churned row from cache) fails loudly.
    Kill/revive (structural membership churn) and capacity flaps ride
    the same run; the parity audit runs every other cycle throughout.
"""

from __future__ import annotations

import copy
import dataclasses
import random

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.loadgen import (
    LoadDriver,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    get_scenario,
)
from karmada_tpu.loadgen.scenarios import ClusterEventSpec
from karmada_tpu.models.cluster import (
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import Placement, ReplicaSchedulingStrategy
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import tensors
from karmada_tpu.resident import ResidentState, RowToken, compare_batches
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


# -- unit-property builders (token-addressable: no affinity terms) -----------
def mk_cluster(i: int) -> Cluster:
    return Cluster(
        metadata=ObjectMeta(name=f"rc-m{i:02d}", resource_version=1),
        spec=ClusterSpec(region="us" if i % 2 else "eu"),
        status=ClusterStatus(resource_summary=ResourceSummary(
            allocatable={
                "cpu": Quantity.from_milli(32000 + 1000 * i),
                "memory": Quantity.from_units(64),
                "pods": Quantity.from_units(110),
            },
            allocated={"cpu": Quantity.from_milli(100 * i)},
        )),
    )


def mk_item(b: int, replicas: int = 2):
    spec = ResourceBindingSpec(
        resource=ObjectReference(
            api_version=GVK[0], kind=GVK[1], namespace="default",
            name=f"app-{b}", uid=f"uid-{b}",
        ),
        replicas=replicas,
        replica_requirements=ReplicaRequirements(resource_request={
            "cpu": Quantity.from_milli(250 if b % 3 else 500),
        }),
        placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type="Divided",
            replica_division_preference="Weighted",
        )),
    )
    return spec, ResourceBindingStatus()


def test_resident_reencodes_exactly_the_churned_rows():
    """Adopt a fleet, then churn random subsets for several cycles: every
    cycle's miss count must equal the churned-binding count, hits the
    rest, with capacity churn on clusters riding the scatter path (no
    rebuild) — and the closing forced audit must be bit-exact."""
    n, nc = 48, 12
    rng = random.Random(7)
    clusters = [mk_cluster(i) for i in range(nc)]
    items = [mk_item(b) for b in range(n)]
    rvs = [1] * n
    state = ResidentState(estimator=GeneralEstimator(), audit_interval=0)

    def tokens():
        return [RowToken(f"rc/{b}", rvs[b]) for b in range(n)]

    state.begin_cycle(clusters)
    state.encode_cycle(items, tokens())  # adoption cycle: all misses
    assert state.misses == n and state.hits == 0
    assert len(state.rows) == n

    for cycle in range(5):
        k = rng.randint(1, n // 2)
        churned = rng.sample(range(n), k)
        for b in churned:
            spec, status = items[b]
            items[b] = (dataclasses.replace(spec, replicas=spec.replicas + 1),
                        status)
            rvs[b] += 1
        # capacity churn on a couple of clusters: status-only => the rv
        # sweep must scatter these lanes, never rebuild
        for lane in rng.sample(range(nc), 2):
            c = copy.deepcopy(clusters[lane])
            c.metadata.resource_version += 1
            rs = c.status.resource_summary
            rs.allocated["cpu"] = Quantity.from_milli(
                rs.allocated["cpu"].milli_value() + 50)
            clusters[lane] = c
        h0, m0 = state.hits, state.misses
        state.begin_cycle(clusters)
        batch = state.encode_cycle(items, tokens())
        assert state.misses - m0 == k, f"cycle {cycle}: re-encoded " \
            f"{state.misses - m0} rows for {k} churned bindings"
        assert state.hits - h0 == n - k
        assert batch.n_bindings == n

    st = state.stats()
    assert st["rebuilds"] == {"init": 1}, \
        f"capacity churn must not rebuild: {st['rebuilds']}"

    # closing audit: the resident batch vs a from-scratch encode, bit-exact
    state.begin_cycle(clusters)
    state.encode_cycle(items, tokens(), audit=True)
    st = state.stats()
    assert st["audits"] == {"ok": 1, "mismatch": 0}, st["last_audit"]

    # direct bit-exact check too (independent of the audit plumbing)
    state.begin_cycle(clusters)
    resident_batch = state.encode_cycle(items, tokens())
    fresh = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 state.estimator)
    assert compare_batches(resident_batch, fresh) == []

    # binding deletion: forget() must drop the row so the next encounter
    # is a miss, not a stale hit
    state.forget("rc/0")
    h0, m0 = state.hits, state.misses
    state.begin_cycle(clusters)
    state.encode_cycle(items, tokens())
    assert state.misses - m0 == 1 and state.hits - h0 == n - 1


def test_resident_structural_churn_falls_back_losslessly():
    """Cluster membership churn (kill then revive) is structural: the
    plane must rebuild, stay correct, and the next steady cycle must be
    resident again (all hits)."""
    n, nc = 24, 8
    clusters = [mk_cluster(i) for i in range(nc)]
    items = [mk_item(b) for b in range(n)]
    toks = [RowToken(f"rs/{b}", 1) for b in range(n)]
    state = ResidentState(estimator=GeneralEstimator(), audit_interval=0)

    state.begin_cycle(clusters)
    state.encode_cycle(items, toks)
    killed = clusters.pop(3)  # membership change => structural
    state.begin_cycle(clusters)
    batch = state.encode_cycle(items, toks)
    st = state.stats()
    assert st["generation"] >= 1 and sum(st["rebuilds"].values()) >= 2
    fresh = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 state.estimator)
    assert compare_batches(batch, fresh) == []

    clusters.insert(3, killed)  # revive => structural again
    state.begin_cycle(clusters)
    state.encode_cycle(items, toks)
    # steady state after the rebuilds: pure hits
    h0, m0 = state.hits, state.misses
    state.begin_cycle(clusters)
    state.encode_cycle(items, toks, audit=True)
    assert state.misses == m0 and state.hits - h0 == n
    assert state.stats()["audits"]["mismatch"] == 0


# -- loadgen churn scenario through the real device scheduler ----------------
def attach_exactness_spy(state: ResidentState):
    """Wrap state.encode_cycle: before each call, derive the expected
    miss count from the pre-call cache (token absent/changed, or no token
    at all => re-encode; resident row at the same rv => hit), then check
    the plane's counters moved by exactly that much."""
    mismatches = []
    orig = state.encode_cycle

    def spy(items, tokens=None, explain=False, audit=None):
        if state.plane is None:
            expected = len(items)  # rebuild fallback: one full encode
        else:
            expected = 0
            for i in range(len(items)):
                tok = tokens[i] if tokens is not None else None
                row = state.rows.get(tok.key) if tok is not None else None
                if row is None or tok is None or row.rv != tok.rv:
                    expected += 1
        before = state.misses
        out = orig(items, tokens, explain=explain, audit=audit)
        got = state.misses - before
        if got != expected:
            mismatches.append(
                {"cycle": state.cycles, "items": len(items),
                 "expected": expected, "reencoded": got})
        return out

    state.encode_cycle = spy
    return mismatches


def run_resident_scenario(scenario, seed: int = 1, audit_interval: int = 2):
    clock = VirtualClock()
    model = ServiceModel()
    plane = ServeSlice(scenario, clock, model, backend="device",
                       resident=True,
                       resident_audit_interval=audit_interval)
    state = plane.scheduler._resident  # noqa: SLF001 — the armed plane
    assert state is not None, "resident plane must arm on the device backend"
    mismatches = attach_exactness_spy(state)
    driver = LoadDriver(plane, scenario, clock=clock, model=model, seed=seed)
    report = driver.run()
    return state, mismatches, report


def test_churn_scenario_delta_path_reencodes_only_churn():
    """The loadgen `churn` scenario (capacity flaps on a rotating cluster,
    compressed mode) against the resident plane: per-cycle re-encode
    exactness, hit rate in the SOAK payload, audit green every other
    cycle, and NO structural rebuilds (flaps are status-only)."""
    scenario = get_scenario("churn")
    state, mismatches, report = run_resident_scenario(scenario)

    assert mismatches == [], mismatches
    assert report["scheduled"] == report["injected"] > 0

    st = state.stats()
    # the parity audit ran repeatedly across the flap events and stayed
    # bit-exact (a mismatch would also force a generation bump)
    assert st["audits"]["ok"] >= 3 and st["audits"]["mismatch"] == 0
    # capacity flaps ride the scatter path: the only rebuild is adoption
    assert st["rebuilds"] == {"init": 1}, st["rebuilds"]
    assert st["resident"] is True

    # the SOAK payload reports the resident plane (hit rate included)
    res = report["resident"]
    assert res is not None and res["enabled"]
    assert res["row_misses"] > 0
    assert res["hit_rate"] is None or 0.0 <= res["hit_rate"] <= 1.0
    assert res["cycles"] == st["cycles"]


def test_churn_scenario_with_kill_revive_keeps_audit_green():
    """Kill/revive membership churn layered onto the flap scenario: the
    structural events must force lossless rebuilds (generation bumps),
    the exactness property must hold through them, and the bit-exact
    audit must stay green for the whole run."""
    base = get_scenario("churn")
    scenario = dataclasses.replace(
        base, name="churn-killrevive",
        events=base.events + (
            ClusterEventSpec(at_frac=0.30, kind="kill", count=1),
            ClusterEventSpec(at_frac=0.75, kind="revive", count=1),
        ))
    state, mismatches, report = run_resident_scenario(scenario)

    assert mismatches == [], mismatches
    assert report["scheduled"] == report["injected"] > 0

    st = state.stats()
    assert st["audits"]["ok"] >= 3 and st["audits"]["mismatch"] == 0
    # kill + revive are structural: at least two rebuilds beyond adoption
    assert sum(st["rebuilds"].values()) >= 3, st["rebuilds"]
    assert st["generation"] >= 2
    assert report["resident"]["row_misses"] > 0
