"""Golden parity: device region-spread path vs the serial DFS pipeline.

Reference: pkg/scheduler/core/spreadconstraint/{group_clusters.go:220-333,
select_groups.go:102-230, select_clusters_by_region.go:27-118}.  The device
path (ops/spread.py) computes grouping/scoring/selection on device and runs
serial.select_groups over group-level scalars, so results must be
bit-identical to ops/serial.schedule for every supported input.
"""

import random

import pytest

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import ResourceBindingStatus, TargetCluster
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.spread import solve_spread
from tests.test_solver_batch import GVK, mk_binding, mk_cluster


def mk_region_cluster(rng, name, region):
    c = mk_cluster(rng, name)
    c.spec.region = region
    if rng.random() < 0.5:
        c.spec.zones = [f"z{rng.randint(0, 2)}"]
    # the harness randomizes taints/deleting; keep a usable fleet
    return c


def mk_spread_placement(rng, names):
    region_min = rng.randint(1, 2)
    scs = [SpreadConstraint(
        spread_by_field=SPREAD_BY_FIELD_REGION,
        min_groups=region_min,
        max_groups=rng.randint(region_min, 3),
    )]
    if rng.random() < 0.7:
        cmin = rng.randint(1, 3)
        scs.append(SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=cmin, max_groups=rng.randint(cmin, 6),
        ))
    if rng.random() < 0.3:
        # provider/zone constraints only filter clusters missing the
        # property (selection stays region+cluster) — they must not knock
        # the binding off the device spread path
        from karmada_tpu.models.policy import (
            SPREAD_BY_FIELD_PROVIDER,
            SPREAD_BY_FIELD_ZONE,
        )

        scs.append(SpreadConstraint(
            spread_by_field=rng.choice([SPREAD_BY_FIELD_PROVIDER,
                                        SPREAD_BY_FIELD_ZONE]),
            min_groups=1, max_groups=rng.randint(1, 3),
        ))
    strat = rng.choice(["dup", "dynamic", "agg"])
    if strat == "dup":
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    elif strat == "dynamic":
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        )
    else:
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED,
        )
    return Placement(spread_constraints=scs, replica_scheduling=rs)


def run_parity(seed, n_clusters=13, n_bindings=16, n_regions=4):
    rng = random.Random(seed)
    names = [f"member-{i:02d}" for i in range(n_clusters)]
    regions = [f"region-{r}" for r in range(n_regions)]
    clusters = [
        mk_region_cluster(rng, nm, rng.choice(regions)) for nm in names
    ]
    placements = [mk_spread_placement(rng, names) for _ in range(4)]
    items = [mk_binding(rng, b, names, placements) for b in range(n_bindings)]

    estimator = GeneralEstimator()
    cal = serial.make_cal_available([estimator])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator)
    spread_idx = [
        i for i in range(len(items))
        if batch.route[i] == tensors.ROUTE_DEVICE_SPREAD
    ]
    assert spread_idx, "scenario must exercise the device spread path"
    got = solve_spread(batch, items, spread_idx)

    for b in spread_idx:
        spec, st = items[b]
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001
            assert isinstance(got[b], type(e)), (
                f"seed={seed} b={b}: serial raised {type(e).__name__}, "
                f"device gave {got[b]!r}"
            )
            continue
        assert not isinstance(got[b], Exception), (
            f"seed={seed} b={b}: serial={want}, device error {got[b]!r}"
        )
        want_map = {tc.name: tc.replicas for tc in want}
        got_map = {tc.name: tc.replicas for tc in got[b]}
        assert got_map == want_map, (
            f"seed={seed} b={b} strat={serial.strategy_type(spec)}: "
            f"serial={want_map} device={got_map}"
        )


@pytest.mark.parametrize("seed", range(15))
def test_spread_parity_random(seed):
    run_parity(seed)


@pytest.mark.parametrize("seed", range(5))
def test_spread_parity_many_regions(seed):
    run_parity(100 + seed, n_clusters=24, n_bindings=12, n_regions=8)


def test_spread_routes_to_host_above_region_cap():
    rng = random.Random(0)
    names = [f"m-{i:02d}" for i in range(40)]
    clusters = [mk_region_cluster(rng, nm, f"r{i}") for i, nm in enumerate(names)]
    placements = [mk_spread_placement(rng, names)]
    items = [mk_binding(rng, 0, names, placements)]
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    assert batch.route[0] == tensors.ROUTE_TOPOLOGY_SPREAD  # 40 regions > 16
