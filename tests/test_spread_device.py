"""Golden parity: device region-spread path vs the serial DFS pipeline.

Reference: pkg/scheduler/core/spreadconstraint/{group_clusters.go:220-333,
select_groups.go:102-230, select_clusters_by_region.go:27-118}.  The device
path (ops/spread.py) computes grouping/scoring/selection on device and runs
serial.select_groups over group-level scalars, so results must be
bit-identical to ops/serial.schedule for every supported input.
"""

import random

import pytest

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import ResourceBindingStatus, TargetCluster
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.spread import solve_spread
from tests.test_solver_batch import GVK, mk_binding, mk_cluster


def mk_region_cluster(rng, name, region):
    c = mk_cluster(rng, name)
    c.spec.region = region
    if rng.random() < 0.5:
        c.spec.zones = [f"z{rng.randint(0, 2)}"]
    # the harness randomizes taints/deleting; keep a usable fleet
    return c


def mk_spread_placement(rng, names):
    region_min = rng.randint(1, 2)
    scs = [SpreadConstraint(
        spread_by_field=SPREAD_BY_FIELD_REGION,
        min_groups=region_min,
        max_groups=rng.randint(region_min, 3),
    )]
    if rng.random() < 0.7:
        cmin = rng.randint(1, 3)
        scs.append(SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=cmin, max_groups=rng.randint(cmin, 6),
        ))
    if rng.random() < 0.3:
        # provider/zone constraints only filter clusters missing the
        # property (selection stays region+cluster) — they must not knock
        # the binding off the device spread path
        from karmada_tpu.models.policy import (
            SPREAD_BY_FIELD_PROVIDER,
            SPREAD_BY_FIELD_ZONE,
        )

        scs.append(SpreadConstraint(
            spread_by_field=rng.choice([SPREAD_BY_FIELD_PROVIDER,
                                        SPREAD_BY_FIELD_ZONE]),
            min_groups=1, max_groups=rng.randint(1, 3),
        ))
    strat = rng.choice(["dup", "dynamic", "agg"])
    if strat == "dup":
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    elif strat == "dynamic":
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        )
    else:
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED,
        )
    return Placement(spread_constraints=scs, replica_scheduling=rs)


def run_parity(seed, n_clusters=13, n_bindings=16, n_regions=4,
               clusters=None, placements=None, items=None):
    rng = random.Random(seed)
    if clusters is None:
        names = [f"member-{i:02d}" for i in range(n_clusters)]
        regions = [f"region-{r}" for r in range(n_regions)]
        clusters = [
            mk_region_cluster(rng, nm, rng.choice(regions)) for nm in names
        ]
    names = [c.name for c in clusters]
    if placements is None:
        placements = [mk_spread_placement(rng, names) for _ in range(4)]
    if items is None:
        items = [mk_binding(rng, b, names, placements)
                 for b in range(n_bindings)]

    estimator = GeneralEstimator()
    cal = serial.make_cal_available([estimator])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator)
    spread_groups = tensors.spread_groups(batch, items)
    spread_idx = [i for g in spread_groups.values() for i in g]
    assert spread_idx, "scenario must exercise the device spread path"
    got = {}
    for (axis, tier), idxs in spread_groups.items():
        got.update(solve_spread(batch, items, idxs, axis=axis, tier=tier))

    for b in spread_idx:
        spec, st = items[b]
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001
            assert isinstance(got[b], type(e)), (
                f"seed={seed} b={b}: serial raised {type(e).__name__}, "
                f"device gave {got[b]!r}"
            )
            continue
        assert not isinstance(got[b], Exception), (
            f"seed={seed} b={b}: serial={want}, device error {got[b]!r}"
        )
        want_map = {tc.name: tc.replicas for tc in want}
        got_map = {tc.name: tc.replicas for tc in got[b]}
        assert got_map == want_map, (
            f"seed={seed} b={b} strat={serial.strategy_type(spec)}: "
            f"serial={want_map} device={got_map}"
        )


@pytest.mark.parametrize("seed", range(15))
def test_spread_parity_random(seed):
    run_parity(seed)


@pytest.mark.parametrize("seed", range(5))
def test_spread_parity_many_regions(seed):
    run_parity(100 + seed, n_clusters=24, n_bindings=12, n_regions=8)


@pytest.mark.parametrize("seed", range(4))
def test_spread_parity_beyond_old_region_cap(seed):
    """40 one-cluster regions: the r4 design's MAX_DEVICE_REGIONS=16 would
    have routed these to host; the segmented group math keeps them on
    device (VERDICT r4 item 3) — parity against the serial DFS pipeline."""
    rng = random.Random(400 + seed)
    names = [f"m-{i:02d}" for i in range(40)]
    clusters = [mk_region_cluster(rng, nm, f"r{i}")
                for i, nm in enumerate(names)]
    run_parity(400 + seed, clusters=clusters, n_bindings=10)


def test_spread_routes_on_device_above_old_region_cap():
    rng = random.Random(0)
    names = [f"m-{i:02d}" for i in range(40)]
    clusters = [mk_region_cluster(rng, nm, f"r{i}") for i, nm in enumerate(names)]
    placements = [mk_spread_placement(rng, names)]
    items = [mk_binding(rng, 0, names, placements)]
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    assert batch.route[0] == tensors.ROUTE_DEVICE_SPREAD  # 40 regions: on device


def test_spread_big_tier_parity():
    """Spread bindings beyond the tier-1 compact caps (replicas > 64 on a
    compact-lane fleet, cluster MaxGroups > 64) run the big-tier assignment
    on device instead of detouring to host (VERDICT r4 item 3)."""
    rng = random.Random(7)
    n = 560  # pads to C=1024 > COMPACT_LANES: the compact tiers are live
    names = [f"m-{i:03d}" for i in range(n)]
    clusters = [mk_region_cluster(rng, nm, f"r{i % 6}")
                for i, nm in enumerate(names)]
    p_wide_sel = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=3),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=2, max_groups=100),
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)),
    )
    p_many_reps = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=2),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=2, max_groups=6),
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED),
    )
    items = [mk_binding(rng, b, names, [p_wide_sel, p_many_reps])
             for b in range(8)]
    for spec, _ in items:
        if spec.placement is p_many_reps:
            spec.replicas = 100 + rng.randint(0, 50)  # > tier-1 division cap
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    assert all(batch.route[i] == tensors.ROUTE_DEVICE_SPREAD_BIG
               for i in range(len(items))), list(batch.route[:len(items)])
    run_parity(7, clusters=clusters, placements=[p_wide_sel, p_many_reps],
               items=items)


def test_spread_beyond_big_caps_routes_to_host():
    rng = random.Random(9)
    n = 560
    names = [f"m-{i:03d}" for i in range(n)]
    clusters = [mk_region_cluster(rng, nm, f"r{i % 6}")
                for i, nm in enumerate(names)]
    p = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=3),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=2, max_groups=600),  # > big cap 512
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)),
    )
    items = [mk_binding(rng, 0, names, [p])]
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    assert batch.route[0] == tensors.ROUTE_COMPACT_CAP


def mk_label_cluster(rng, name, value, key="topology.karmada.io/ring"):
    c = mk_cluster(rng, name)
    if value is not None:
        c.metadata.labels[key] = value
    return c


def mk_label_placement(rng, key="topology.karmada.io/ring"):
    gmin = rng.randint(1, 2)
    scs = [SpreadConstraint(spread_by_label=key, min_groups=gmin,
                            max_groups=rng.randint(gmin, 3))]
    if rng.random() < 0.7:
        cmin = rng.randint(1, 3)
        scs.append(SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=cmin, max_groups=rng.randint(cmin, 6)))
    rs = ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
        replica_division_preference=REPLICA_DIVISION_WEIGHTED,
        weight_preference=ClusterPreferences(
            dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
    ) if rng.random() < 0.5 else ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    return Placement(spread_constraints=scs, replica_scheduling=rs)


@pytest.mark.parametrize("seed", range(8))
def test_spread_by_label_parity(seed):
    """SpreadByLabel grouping (framework extension — the reference fails
    it, select_clusters.go:55): device label-axis group math must be
    bit-identical to the extended serial pipeline."""
    rng = random.Random(800 + seed)
    names = [f"m-{i:02d}" for i in range(14)]
    values = [f"ring-{v}" for v in range(4)]
    clusters = [
        mk_label_cluster(rng, nm,
                         rng.choice(values) if rng.random() < 0.85 else None)
        for nm in names
    ]
    placements = [mk_label_placement(rng) for _ in range(3)]
    run_parity(800 + seed, clusters=clusters, placements=placements,
               n_bindings=12)


def test_spread_by_label_routes_on_device():
    rng = random.Random(1)
    names = [f"m-{i}" for i in range(6)]
    clusters = [mk_label_cluster(rng, nm, f"v{i % 2}")
                for i, nm in enumerate(names)]
    placements = [mk_label_placement(rng)]
    items = [mk_binding(rng, 0, names, placements)]
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    assert batch.route[0] == tensors.ROUTE_DEVICE_SPREAD
    key = "topology.karmada.io/ring"
    assert key in batch.label_axes
    gid, vals = batch.label_axes[key]
    assert set(vals) == {"v0", "v1"}
    assert tensors.spread_axis_of(placements[0]) == key
