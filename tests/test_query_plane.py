"""L5 query plane: multi-cluster cache, cluster proxy + unified auth,
metrics provider.

Reference: pkg/search/proxy/store/multi_cluster_cache.go,
pkg/registry/cluster/storage/proxy.go:73,
pkg/controllers/unifiedauth/unified_auth_controller.go:69,
pkg/metricsadapter/provider/.
"""

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DUPLICATED,
    ClusterAffinity,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.search import (
    ResourceRegistry,
    ResourceRegistrySelector,
    ResourceRegistrySpec,
)
from karmada_tpu.search import CACHED_FROM_ANNOTATION, ProxyDenied


def deployment(name, ns="default", replicas=2):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def registry(clusters=None):
    return ResourceRegistry(
        metadata=ObjectMeta(name="all-deployments"),
        spec=ResourceRegistrySpec(
            target_cluster=ClusterAffinity(cluster_names=clusters or []),
            resource_selectors=[
                ResourceRegistrySelector(api_version="apps/v1", kind="Deployment")
            ],
        ),
    )


def dup_policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED
                )
            ),
        ),
    )


@pytest.fixture
def cp():
    plane = ControlPlane(backend="serial")
    for m in ("m1", "m2", "m3"):
        plane.add_member(m, cpu_milli=64_000)
    plane.tick()
    return plane


def test_cache_fans_in_from_selected_clusters(cp):
    cp.store.create(registry())
    # propagate a deployment to all three members through the real pipeline
    cp.store.create(dup_policy())
    cp.apply(deployment("web"))
    cp.tick()
    entries = cp.search_cache.list("Deployment", "default")
    clusters = {e.metadata.annotations[CACHED_FROM_ANNOTATION] for e in entries}
    assert clusters == {"m1", "m2", "m3"}
    got = cp.search_cache.get("Deployment", "default", "web", cluster="m2")
    assert got is not None and got.manifest["spec"]["replicas"] == 2


def test_cache_respects_registry_target_clusters(cp):
    cp.store.create(registry(clusters=["m1"]))
    cp.store.create(dup_policy())
    cp.apply(deployment("web"))
    cp.tick()
    clusters = {
        e.metadata.annotations[CACHED_FROM_ANNOTATION]
        for e in cp.search_cache.list("Deployment")
    }
    assert clusters == {"m1"}


def test_cache_drops_on_member_delete(cp):
    cp.store.create(registry())
    cp.tick()
    # applied directly on the member (not via a Work, which the work-status
    # controller would heal by recreating)
    cp.members["m1"].apply(deployment("local-only"))
    assert len(cp.search_cache.list("Deployment")) == 1
    cp.members["m1"].delete("Deployment", "default", "local-only")
    assert cp.search_cache.list("Deployment") == []


def test_cache_watch_streams_changes(cp):
    cp.store.create(registry())
    cp.tick()
    seen = []
    cp.search_cache.watch(lambda t, obj, c: seen.append((t, obj.name, c)))
    cp.members["m2"].apply(deployment("direct"))
    assert ("UPSERT", "direct", "m2") in seen


def test_proxy_roundtrip_with_unified_auth(cp):
    cp.tick()  # unified-auth sync
    handle = cp.proxy("m1")
    handle.apply(deployment("via-proxy"))
    assert cp.members["m1"].get("Deployment", "default", "via-proxy") is not None
    assert handle.get("Deployment", "default", "via-proxy") is not None


def test_proxy_denies_unknown_subject(cp):
    cp.tick()
    with pytest.raises(ProxyDenied, match="not authorized"):
        cp.proxy("m1", subject="mallory")


def test_proxy_grant_then_allowed(cp):
    cp.tick()
    cp.unified_auth.grant("alice")
    cp.tick()
    assert cp.proxy("m1", subject="alice").list("Deployment") == []


def test_proxy_unknown_cluster(cp):
    with pytest.raises(ProxyDenied, match="unknown cluster"):
        cp.proxy("nope")


def test_metrics_provider_merges_pods_across_clusters(cp):
    cp.store.create(dup_policy())
    cp.apply(deployment("web", replicas=3))
    cp.tick()
    cp.members["m1"].set_load("Deployment", "default", "web", {"cpu": 80})
    samples = cp.metrics_provider.pod_metrics("Deployment", "default", "web")
    by_cluster = {}
    for s in samples:
        by_cluster.setdefault(s["cluster"], []).append(s)
    assert set(by_cluster) == {"m1", "m2", "m3"}
    assert len(by_cluster["m1"]) == 3
    assert by_cluster["m1"][0]["usage"]["cpu"] == 80
    # idle default: 10% of the 100m request
    assert by_cluster["m2"][0]["usage"]["cpu"] == 10


def test_metrics_provider_skips_unhealthy(cp):
    cp.store.create(dup_policy())
    cp.apply(deployment("web"))
    cp.tick()
    cp.members["m3"].healthy = False
    samples = cp.metrics_provider.pod_metrics("Deployment", "default", "web")
    assert {s["cluster"] for s in samples} == {"m1", "m2"}


def test_backend_store_receives_cache_events(cp):
    """backendstore seam (pkg/search/backendstore): a registry naming a
    registered external backend kind streams every cached upsert/delete."""
    from karmada_tpu.models.search import BackendStoreConfig
    from karmada_tpu.search.backend import BackendStore, register_backend_factory

    events = []

    class Recording(BackendStore):
        def upsert(self, cluster, obj):
            events.append(("upsert", cluster, obj.name))

        def delete(self, cluster, obj):
            events.append(("delete", cluster, obj.name))

    register_backend_factory("Recording", lambda cfg: Recording())
    reg = registry(clusters=["m1"])
    reg.spec.backend_store = BackendStoreConfig(kind="Recording")
    cp.store.create(reg)
    cp.tick()
    cp.members["m1"].apply(deployment("indexed"))
    assert ("upsert", "m1", "indexed") in events
    cp.members["m1"].delete("Deployment", "default", "indexed")
    assert ("delete", "m1", "indexed") in events


def test_unknown_backend_kind_does_not_break_cache(cp):
    from karmada_tpu.models.search import BackendStoreConfig

    reg = registry(clusters=["m1"])
    reg.spec.backend_store = BackendStoreConfig(kind="OpenSearch")  # not bundled
    cp.store.create(reg)
    cp.tick()
    cp.members["m1"].apply(deployment("still-cached"))
    assert cp.search_cache.get("Deployment", "default", "still-cached") is not None


def test_backend_replays_existing_objects_on_late_registration(cp):
    """A backend added for an already-synced pair receives the initial
    list, not just future deltas (backendstore informer semantics)."""
    from karmada_tpu.models.meta import ObjectMeta as OM
    from karmada_tpu.models.search import (
        BackendStoreConfig,
        ResourceRegistry,
        ResourceRegistrySelector,
        ResourceRegistrySpec,
    )
    from karmada_tpu.search.backend import BackendStore, register_backend_factory

    cp.store.create(registry(clusters=["m1"]))
    cp.tick()
    cp.members["m1"].apply(deployment("pre-existing"))

    events = []

    class Late(BackendStore):
        def upsert(self, cluster, obj):
            events.append((cluster, obj.name))

        def delete(self, cluster, obj):
            pass

    register_backend_factory("Late", lambda cfg: Late())
    cp.store.create(ResourceRegistry(
        metadata=OM(name="late-reg"),
        spec=ResourceRegistrySpec(
            resource_selectors=[
                ResourceRegistrySelector(api_version="apps/v1", kind="Deployment")
            ],
            backend_store=BackendStoreConfig(kind="Late"),
        ),
    ))
    cp.tick()
    assert ("m1", "pre-existing") in events


def test_backend_scoped_to_its_registry_pairs(cp):
    """Backends only see THEIR registry's (cluster, kind) selections —
    another registry's resources never leak into an external sink."""
    from karmada_tpu.models.meta import ObjectMeta as OM
    from karmada_tpu.models.search import (
        BackendStoreConfig,
        ResourceRegistry,
        ResourceRegistrySelector,
        ResourceRegistrySpec,
    )
    from karmada_tpu.models.policy import ClusterAffinity
    from karmada_tpu.search.backend import BackendStore, register_backend_factory

    events = []

    class Scoped(BackendStore):
        def upsert(self, cluster, obj):
            events.append((cluster, obj.KIND, obj.name))

        def delete(self, cluster, obj):
            pass

    register_backend_factory("Scoped", lambda cfg: Scoped())
    cp.store.create(ResourceRegistry(
        metadata=OM(name="m1-deployments"),
        spec=ResourceRegistrySpec(
            target_cluster=ClusterAffinity(cluster_names=["m1"]),
            resource_selectors=[
                ResourceRegistrySelector(api_version="apps/v1", kind="Deployment")
            ],
            backend_store=BackendStoreConfig(kind="Scoped"),
        ),
    ))
    cp.store.create(ResourceRegistry(
        metadata=OM(name="m2-secrets"),
        spec=ResourceRegistrySpec(
            target_cluster=ClusterAffinity(cluster_names=["m2"]),
            resource_selectors=[
                ResourceRegistrySelector(api_version="v1", kind="Secret")
            ],
        ),
    ))
    cp.tick()
    cp.members["m2"].apply({
        "apiVersion": "v1", "kind": "Secret",
        "metadata": {"name": "db-creds", "namespace": "default"},
        "data": {"p": "x"},
    })
    cp.members["m1"].apply(deployment("mine"))
    kinds = {(c, k) for (c, k, _) in events}
    assert ("m1", "Deployment") in kinds
    assert ("m2", "Secret") not in kinds, "other registry's resources leaked"


def test_sqlite_fts_backend_roundtrip(cp, tmp_path):
    """The bundled external sink (SqliteFTS, the OpenSearch analog —
    pkg/search/backendstore/opensearch.go): a registry naming it streams
    upserts/deletes into a real file index that answers full-text queries."""
    from karmada_tpu.models.search import BackendStoreConfig

    db = str(tmp_path / "index.db")
    reg = registry(clusters=["m1"])
    reg.spec.backend_store = BackendStoreConfig(kind="SqliteFTS",
                                                addresses=[db])
    cp.store.create(reg)
    cp.tick()
    cp.members["m1"].apply(deployment("searchable-web"))
    backend = cp.search_cache.backend_of("all-deployments")
    assert backend is not None and backend.count() >= 1

    hits = backend.query("searchable-web")
    assert any(h["name"] == "searchable-web" for h in hits)
    assert hits[0]["cluster"] == "m1"
    assert hits[0]["object"]["kind"] == "Deployment"
    # filters narrow
    assert backend.query("searchable-web", kind="Deployment")
    assert not backend.query("searchable-web", kind="Service")
    assert not backend.query("no-such-term-anywhere")

    # deletes drop the document
    cp.members["m1"].delete("Deployment", "default", "searchable-web")
    assert not backend.query("searchable-web")

    # the index survives on disk: a fresh handle over the same file serves
    # remaining documents (external-engine persistence, unlike the cache)
    cp.members["m1"].apply(deployment("persistent-doc"))
    from karmada_tpu.search.fts import SqliteFTSBackend

    reopened = SqliteFTSBackend(db)
    assert reopened.query("persistent-doc")
    reopened.close()


def test_fts_query_over_http(cp, tmp_path):
    """GET /search/query runs full-text search against a registry's
    external backend through the served query plane."""
    import json as _json
    import urllib.request

    from karmada_tpu.models.search import BackendStoreConfig
    from karmada_tpu.search.httpapi import QueryPlaneServer

    reg = registry(clusters=["m1"])
    reg.spec.backend_store = BackendStoreConfig(
        kind="SqliteFTS", addresses=[str(tmp_path / "i.db")])
    cp.store.create(reg)
    cp.tick()
    cp.members["m1"].apply(deployment("http-findable"))
    srv = QueryPlaneServer(cp.store, cp.members, cp.cluster_proxy,
                           search_cache=cp.search_cache,
                           metrics_provider=cp.metrics_provider)
    url = srv.start()
    try:
        with urllib.request.urlopen(
                url + "/search/query?registry=all-deployments&q=http-findable",
                timeout=10) as r:
            hits = _json.loads(r.read())
        assert any(h["name"] == "http-findable" for h in hits)
    finally:
        srv.stop()


def test_metrics_provider_families(cp):
    """The three provider families (pkg/metricsadapter/provider/): node
    metrics fan-out, custom metrics by name/selector with multi-cluster
    merge, and labeled external series."""
    cp.store.create(registry())
    cp.apply_policy(dup_policy())
    cp.apply(deployment("svc"))
    cp.tick()
    mp = cp.metrics_provider

    # resource metrics: every healthy member contributes its node(s)
    nodes = mp.node_metrics()
    assert {n["cluster"] for n in nodes} == {"m1", "m2", "m3"}
    assert all(n["allocatable"]["cpu"] > 0 for n in nodes)
    cp.members["m2"].healthy = False
    assert {n["cluster"] for n in mp.node_metrics()} == {"m1", "m3"}
    cp.members["m2"].healthy = True

    # custom metrics: member-served series merge across clusters
    cp.members["m1"].custom_metrics[
        ("Deployment", "default", "svc", "requests_per_s")] = 120.0
    cp.members["m2"].custom_metrics[
        ("Deployment", "default", "svc", "requests_per_s")] = 80.0
    got = mp.custom_metric_by_name("Deployment", "default", "svc",
                                   "requests_per_s")
    assert got["value"] == 200.0
    assert {s["cluster"]: s["value"] for s in got["samples"]} == {
        "m1": 120.0, "m2": 80.0}
    assert mp.custom_metric_by_name("Deployment", "default", "svc",
                                    "nope") is None
    assert mp.list_all_metrics() == ["requests_per_s"]
    # selector path: matches on the member object's labels
    by_sel = mp.custom_metric_by_selector("Deployment", "default", None,
                                          "requests_per_s")
    assert len(by_sel) == 1 and by_sel[0]["value"] == 200.0
    assert mp.custom_metric_by_selector(
        "Deployment", "default", {"tier": "gold"}, "requests_per_s") == []

    # external metrics: labeled series + scalar back-compat
    mp.external["queue_depth"] = [
        {"labels": {"queue": "payments"}, "value": 31.0},
        {"labels": {"queue": "emails"}, "value": 7.0},
    ]
    assert mp.external_metric("queue_depth") == 38.0
    vals = mp.external_metric_values("queue_depth", {"queue": "payments"})
    assert vals == [{"labels": {"queue": "payments"}, "value": 31.0}]
    mp.external["flat"] = 5
    assert mp.external_metric_values("flat") == [{"labels": {}, "value": 5.0}]


def test_metrics_families_over_http(cp):
    import json as _json
    import urllib.request

    from karmada_tpu.search.httpapi import QueryPlaneServer

    cp.store.create(registry())
    cp.apply_policy(dup_policy())
    cp.apply(deployment("svc"))
    cp.tick()
    cp.members["m1"].custom_metrics[
        ("Deployment", "default", "svc", "rps")] = 9.0
    cp.metrics_provider.external["queue_depth"] = [
        {"labels": {"queue": "a"}, "value": 3.0}]
    srv = QueryPlaneServer(cp.store, cp.members, cp.cluster_proxy,
                           search_cache=cp.search_cache,
                           metrics_provider=cp.metrics_provider)
    url = srv.start()
    try:
        def get(path):
            with urllib.request.urlopen(url + path, timeout=10) as r:
                return _json.loads(r.read())
        assert {n["cluster"] for n in get("/metrics-adapter/nodes")} == {
            "m1", "m2", "m3"}
        assert get("/metrics-adapter/custom-list") == ["rps"]
        got = get("/metrics-adapter/custom/Deployment/default/svc/rps")
        assert got["value"] == 9.0
        ext = get("/metrics-adapter/external/queue_depth?queue=a")
        assert ext["value"] == 3.0 and ext["values"][0]["labels"] == {"queue": "a"}
    finally:
        srv.stop()
