"""WorkloadRebalancer, ClusterTaintPolicy, Remedy, FederatedResourceQuota."""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.extras import (
    ClusterTaintPolicy,
    ClusterTaintPolicySpec,
    DecisionMatch,
    FederatedResourceQuota,
    FederatedResourceQuotaSpec,
    MatchCondition,
    ObjectReferenceSpec,
    Remedy,
    RemedySpec,
    StaticClusterAssignment,
    TaintSpec,
    WorkloadRebalancer,
    WorkloadRebalancerSpec,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.utils.quantity import Quantity


def _policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version="apps/v1",
                                                 kind="Deployment")],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            )),
        ),
    )


def _deployment(replicas=4):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "app", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "image": "i",
             "resources": {"requests": {"cpu": "100m", "memory": "128Mi"}}}]}}},
    }


def test_workload_rebalancer_triggers_fresh_reschedule():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.add_member("m2")
    cp.tick()
    cp.apply_policy(_policy())
    cp.apply(_deployment())
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert rb.spec.reschedule_triggered_at is None

    wr = WorkloadRebalancer(
        metadata=ObjectMeta(name="rebalance-now"),
        spec=WorkloadRebalancerSpec(workloads=[ObjectReferenceSpec(
            api_version="apps/v1", kind="Deployment",
            namespace="default", name="app")]),
    )
    cp.store.create(wr)
    cp.tick()
    wr = cp.store.get(WorkloadRebalancer.KIND, "", "rebalance-now")
    assert wr.status.finish_time is not None
    assert wr.status.observed_workloads[0].result == "Successful"
    rb = cp.store.get(ResourceBinding.KIND, "default", "app-deployment")
    assert rb.spec.reschedule_triggered_at is not None
    # still fully scheduled after the fresh pass
    assert sum(t.replicas for t in rb.spec.clusters) == 4


def test_cluster_taint_policy_adds_and_removes():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    cp.store.create(ClusterTaintPolicy(
        metadata=ObjectMeta(name="notready-taint"),
        spec=ClusterTaintPolicySpec(
            add_on_conditions=[MatchCondition(
                condition_type="Ready", operator="In", status_values=["False"])],
            remove_on_conditions=[MatchCondition(
                condition_type="Ready", operator="In", status_values=["True"])],
            taints=[TaintSpec(key="example.io/unhealthy", effect="NoSchedule")],
        ),
    ))
    cp.tick()
    cluster = cp.store.get("Cluster", "", "m1")
    assert not any(t.key == "example.io/unhealthy" for t in cluster.spec.taints)

    cp.member("m1").healthy = False
    cp.tick()
    cluster = cp.store.get("Cluster", "", "m1")
    assert any(t.key == "example.io/unhealthy" for t in cluster.spec.taints)

    cp.member("m1").healthy = True
    cp.tick()
    cluster = cp.store.get("Cluster", "", "m1")
    assert not any(t.key == "example.io/unhealthy" for t in cluster.spec.taints)


def test_remedy_sets_cluster_actions():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.tick()
    cp.store.create(Remedy(
        metadata=ObjectMeta(name="traffic-off"),
        spec=RemedySpec(
            decision_matches=[DecisionMatch(
                cluster_condition_type="Ready", cluster_condition_status="False")],
            actions=["TrafficControl"],
        ),
    ))
    cp.tick()
    assert cp.store.get("Cluster", "", "m1").status.remedy_actions == []
    cp.member("m1").healthy = False
    cp.tick()
    assert cp.store.get("Cluster", "", "m1").status.remedy_actions == ["TrafficControl"]
    cp.member("m1").healthy = True
    cp.tick()
    assert cp.store.get("Cluster", "", "m1").status.remedy_actions == []


def test_federated_resource_quota_renders_per_cluster():
    cp = ControlPlane()
    cp.add_member("m1")
    cp.add_member("m2")
    cp.tick()
    cp.store.create(FederatedResourceQuota(
        metadata=ObjectMeta(name="team-quota", namespace="default"),
        spec=FederatedResourceQuotaSpec(
            overall={"cpu": Quantity.parse("20")},
            static_assignments=[
                StaticClusterAssignment("m1", {"cpu": Quantity.parse("12")}),
                StaticClusterAssignment("m2", {"cpu": Quantity.parse("8")}),
            ],
        ),
    ))
    cp.tick()
    for m, want in (("m1", "12"), ("m2", "8")):
        rq = cp.member(m).get("ResourceQuota", "default", "team-quota")
        assert rq is not None
        assert rq.manifest["spec"]["hard"]["cpu"] == want
    frq = cp.store.get(FederatedResourceQuota.KIND, "default", "team-quota")
    assert {c.cluster_name for c in frq.status.aggregated_status} == {"m1", "m2"}
