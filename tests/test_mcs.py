"""MCS end to end: a service exported from one member resolves in another.

Reference: pkg/controllers/multiclusterservice/{mcs_controller.go:71,
endpointslice_collect_controller.go:87, endpointslice_dispatch_controller.go:68}
and pkg/controllers/mcs/service_export_controller.go:103.
"""

import pytest

from karmada_tpu.controllers.mcs import (
    ORIGIN_CLUSTER_ANNOTATION,
    SERVICE_NAME_LABEL,
    _collected_name,
)
from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.networking import (
    ExposureRange,
    MultiClusterService,
    MultiClusterServiceSpec,
    ServiceExport,
)
from karmada_tpu.models.meta import ObjectMeta


def service(name="web", ns="default"):
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"ports": [{"port": 80}], "selector": {"app": name}},
    }


def endpoint_slice(name, service_name, ns="default", ips=("10.0.0.1",)):
    return {
        "apiVersion": "discovery.k8s.io/v1", "kind": "EndpointSlice",
        "metadata": {"name": name, "namespace": ns,
                     "labels": {SERVICE_NAME_LABEL: service_name}},
        "addressType": "IPv4",
        "endpoints": [{"addresses": list(ips)}],
        "ports": [{"port": 80}],
    }


def mcs(name="web", ns="default", providers=None, consumers=None):
    return MultiClusterService(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=MultiClusterServiceSpec(
            provider_clusters=(
                [ExposureRange(cluster_names=providers)] if providers else []
            ),
            consumer_clusters=(
                [ExposureRange(cluster_names=consumers)] if consumers else []
            ),
        ),
    )


@pytest.fixture
def cp():
    plane = ControlPlane(backend="serial")
    plane.add_member("m1")
    plane.add_member("m2")
    plane.add_member("m3")
    plane.tick()
    return plane


def test_mcs_propagates_service_everywhere(cp):
    cp.apply(service())
    cp.store.create(mcs())
    cp.tick()
    for m in ("m1", "m2", "m3"):
        assert cp.members[m].get("Service", "default", "web") is not None


def test_service_exported_from_m1_resolvable_in_m2(cp):
    """The headline flow: provider m1's endpoints appear in consumer m2."""
    cp.apply(service())
    cp.store.create(mcs(providers=["m1"], consumers=["m2"]))
    cp.tick()
    # m1's endpoint controller publishes a local slice for the service
    cp.members["m1"].apply(endpoint_slice("web-abc", "web", ips=("10.1.1.5",)))
    cp.tick()
    # collected upward, tagged with origin
    up = cp.store.try_get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc"))
    assert up is not None
    assert up.metadata.annotations[ORIGIN_CLUSTER_ANNOTATION] == "m1"
    # dispatched into the consumer
    down = cp.members["m2"].get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc"))
    assert down is not None
    assert down.manifest["endpoints"][0]["addresses"] == ["10.1.1.5"]
    # never dispatched back to the origin or to non-consumers
    assert cp.members["m1"].get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None
    assert cp.members["m3"].get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None


def test_slice_removal_propagates(cp):
    cp.apply(service())
    cp.store.create(mcs(providers=["m1"], consumers=["m2"]))
    cp.tick()
    cp.members["m1"].apply(endpoint_slice("web-abc", "web"))
    cp.tick()
    assert cp.members["m2"].get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is not None
    cp.members["m1"].delete("EndpointSlice", "default", "web-abc")
    cp.tick()
    assert cp.store.try_get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None
    assert cp.members["m2"].get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None


def test_mcs_delete_cleans_up(cp):
    cp.apply(service())
    cp.store.create(mcs(providers=["m1"], consumers=["m2"]))
    cp.tick()
    cp.members["m1"].apply(endpoint_slice("web-abc", "web"))
    cp.tick()
    cp.store.delete(MultiClusterService.KIND, "default", "web")
    cp.tick()
    assert cp.store.try_get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None
    assert cp.members["m2"].get("Service", "default", "web") is None


def test_unexported_service_not_collected(cp):
    cp.apply(service())
    cp.tick()
    cp.members["m1"].apply(endpoint_slice("web-abc", "web"))
    cp.tick()
    assert cp.store.try_get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is None


def test_service_export_marks_for_collection(cp):
    """The mcs.k8s.io flavor: a ServiceExport alone triggers collection."""
    cp.apply(service())
    cp.store.create(ServiceExport(metadata=ObjectMeta(name="web", namespace="default")))
    cp.tick()
    cp.members["m1"].apply(endpoint_slice("web-abc", "web"))
    cp.tick()
    assert cp.store.try_get("EndpointSlice", "default", _collected_name("m1", "default", "web-abc")) is not None


def test_default_mcs_no_collect_dispatch_livelock(cp):
    """Default MCS (every cluster is provider AND consumer): dispatched
    slices carry the managed-by annotation and must never be re-collected
    (regression: collect<->dispatch bounced new imported-... names forever
    and the runtime failed to quiesce)."""
    cp.apply(service())
    cp.store.create(mcs())  # no explicit providers/consumers
    cp.tick()
    cp.members["m1"].apply(endpoint_slice("web-abc", "web", ips=("10.9.9.9",)))
    cp.tick()
    collected = [
        o for o in cp.store.list("EndpointSlice", "default")
        if o.name.startswith("imported-")
    ]
    assert len(collected) == 1  # exactly one upward copy, no cascade
    name = _collected_name("m1", "default", "web-abc")
    assert cp.members["m2"].get("EndpointSlice", "default", name) is not None
    assert cp.members["m3"].get("EndpointSlice", "default", name) is not None


def test_provider_scoping(cp):
    """Slices from a non-provider cluster are not collected."""
    cp.apply(service())
    cp.store.create(mcs(providers=["m1"], consumers=["m2"]))
    cp.tick()
    cp.members["m3"].apply(endpoint_slice("web-xyz", "web"))
    cp.tick()
    assert cp.store.try_get("EndpointSlice", "default", _collected_name("m3", "default", "web-xyz")) is None


def test_mci_renders_ingress_to_consumer_clusters(cp):
    from karmada_tpu.models.networking import (
        MultiClusterIngress,
        MultiClusterIngressSpec,
    )

    cp.apply(service())
    cp.store.create(mcs(providers=["m1"], consumers=["m2"]))
    cp.store.create(MultiClusterIngress(
        metadata=ObjectMeta(name="web-ingress", namespace="default"),
        spec=MultiClusterIngressSpec(rules=[{
            "host": "web.example.com",
            "http": {"paths": [{"path": "/", "backend": {
                "service": {"name": "web", "port": {"number": 80}}}}]},
        }]),
    ))
    cp.tick()
    # the derived Ingress lands on the MCS consumer cluster only
    ing = cp.members["m2"].get("Ingress", "default", "web-ingress")
    assert ing is not None
    assert ing.manifest["spec"]["rules"][0]["host"] == "web.example.com"
    assert cp.members["m1"].get("Ingress", "default", "web-ingress") is None
    assert cp.members["m3"].get("Ingress", "default", "web-ingress") is None
    # deleting the MCI cleans the Works up
    cp.store.delete(MultiClusterIngress.KIND, "default", "web-ingress")
    cp.tick()
    assert cp.members["m2"].get("Ingress", "default", "web-ingress") is None


def test_mci_without_mcs_goes_everywhere(cp):
    from karmada_tpu.models.networking import (
        MultiClusterIngress,
        MultiClusterIngressSpec,
    )

    cp.store.create(MultiClusterIngress(
        metadata=ObjectMeta(name="wide", namespace="default"),
        spec=MultiClusterIngressSpec(
            default_backend={"service": {"name": "web", "port": {"number": 80}}}
        ),
    ))
    cp.tick()
    for m in ("m1", "m2", "m3"):
        assert cp.members[m].get("Ingress", "default", "wide") is not None


def test_pull_member_slices_collected_by_agent(cp):
    """Pull-mode members are unreachable from the control plane: their
    EndpointSlices are collected by the AGENT's scoped controller
    (agent.go registers endpointsliceCollect), not the central one."""
    cp.add_member("pull-1", sync_mode="Pull")
    cp.tick()
    # the CENTRAL collector must not watch the pull member
    assert "pull-1" not in cp.eps_collect.members
    assert "pull-1" not in cp.eps_collect._subscribed
    # ...but the agent's scoped collector does
    assert "pull-1" in cp.agents["pull-1"].eps_collect.members

    cp.apply(service())
    cp.store.create(ServiceExport(metadata=ObjectMeta(name="web",
                                                      namespace="default")))
    cp.tick()
    cp.members["pull-1"].apply(endpoint_slice("web-xyz", "web"))
    cp.tick()
    assert cp.store.try_get(
        "EndpointSlice", "default",
        _collected_name("pull-1", "default", "web-xyz")) is not None

    # agent teardown unwinds the collection wiring
    cp.agents["pull-1"].stop()
    cp.members["pull-1"].apply(endpoint_slice("web-late", "web"))
    cp.tick()
    assert cp.store.try_get(
        "EndpointSlice", "default",
        _collected_name("pull-1", "default", "web-late")) is None
