"""Pipelined chunk executor (scheduler/pipeline.py): the production loop.

The executor is the ONE scheduling hot loop both scheduler/service
(_solve_device) and bench.py drive.  Covered here:

  * parity: chunked+carry output bit-identical to the pre-pipeline
    single-dispatch path on mixed routes (device, region-spread, big-tier,
    host-serial rows) with ample capacity;
  * sequential equivalence: chunked execution with one-binding-per-wave
    chunks and chunk-to-chunk carry equals ONE solve with one binding per
    wave — the carry transports consumed capacity exactly;
  * chunk-carry accounting: chunk k+1 rejects capacity chunk k consumed,
    including across a vocabulary change and a vocabulary GAP (a resource
    absent from an intermediate chunk's vocabulary);
  * cancellation: a cancelled cycle stops at the next stage boundary and
    writes nothing (no results, no metrics) after the event is set;
  * a fast 3-chunk smoke over the bench mix so the executor runs on every
    tier-1 pass without a device (CPU platform via tests/conftest.py).
"""

import random
import threading

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.scheduler import metrics as sm
from karmada_tpu.scheduler import pipeline
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


def _fleet(n, seed=0):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, n)
    return clusters, tensors.ClusterIndex.build(clusters)


def _results_equal(want, got, ctx=""):
    if isinstance(want, Exception):
        assert isinstance(got, type(want)), (ctx, want, got)
        return
    assert not isinstance(got, Exception), (ctx, got)
    assert ({t.name: t.replicas for t in got}
            == {t.name: t.replicas for t in want}), ctx


def _single_dispatch_reference(items, cindex, estimator, waves):
    """The pre-pipeline _solve_device: one monolithic encode + one compact
    dispatch, spread/big sub-solves, shared decode.  Returns {index:
    result} for device-owned rows only — the executor's exact contract."""
    from karmada_tpu.ops.solver import solve_big, solve_compact
    from karmada_tpu.ops.spread import solve_spread

    out = {}
    batch = tensors.encode_batch(items, cindex, estimator)
    for (axis, tier), idxs in tensors.spread_groups(batch, items).items():
        out.update(solve_spread(batch, items, idxs, waves=waves,
                                axis=axis, tier=tier))
    big_idx = [i for i in range(len(items))
               if batch.route[i] == tensors.ROUTE_DEVICE_BIG]
    out.update(solve_big(items, big_idx, cindex, estimator, None,
                         waves=waves))
    idx, val, status, _ = solve_compact(batch, waves=waves)
    decoded = tensors.decode_compact(batch, idx, val, status, items=items)
    for i in range(len(items)):
        if batch.route[i] == tensors.ROUTE_DEVICE:
            out[i] = decoded[i]
    return out, batch


def _mixed_items():
    """A route matrix over ample capacity: plain device strategies, a
    region spread (device group math + host DFS), and two host-serial
    classes (vanished prev cluster; provider-only spread)."""

    def spec_of(b, placement, **kw):
        return (
            ResourceBindingSpec(
                resource=ObjectReference(
                    api_version=GVK[0], kind=GVK[1], namespace="d",
                    name=f"a{b}", uid=f"uid-{b}"),
                replicas=kw.pop("replicas", 4),
                replica_requirements=ReplicaRequirements(resource_request={
                    "cpu": Quantity.from_milli(100)}),
                placement=placement, **kw,
            ),
            ResourceBindingStatus(),
        )

    divided = Placement(replica_scheduling=ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
        replica_division_preference=REPLICA_DIVISION_WEIGHTED,
        weight_preference=ClusterPreferences(
            dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
    duplicated = Placement(replica_scheduling=ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED))
    aggregated = Placement(
        spread_constraints=[SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=2, max_groups=5)],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED))
    region_spread = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=3),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=2, max_groups=5),
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
    provider_only = Placement(
        spread_constraints=[SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_PROVIDER,
            min_groups=1, max_groups=2)],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))

    items = []
    for b in range(8):
        items.append(spec_of(b, [divided, duplicated, aggregated,
                                 region_spread][b % 4]))
    # host-serial rows: a previous assignment naming a vanished cluster,
    # and the reference's unsupported provider-only spread selection
    items.append(spec_of(8, divided,
                         clusters=[TargetCluster(name="gone", replicas=1)]))
    items.append(spec_of(9, provider_only))
    items.append(spec_of(10, region_spread))
    items.append(spec_of(11, duplicated, replicas=2))
    return items


def test_parity_mixed_routes_chunked_vs_single_dispatch():
    """Executor output (3 chunks, carry on) must be bit-identical to the
    pre-pipeline single-dispatch path on a mixed-route matrix, and
    host-serial rows must stay absent from both results."""
    clusters, cindex = _fleet(24)
    est = GeneralEstimator()
    items = _mixed_items()

    want, batch = _single_dispatch_reference(items, cindex, est, waves=2)
    res = pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2,
                                carry=True)
    routes = batch.route
    host_rows = [i for i in range(len(items))
                 if routes[i] not in pipeline.DEVICE_ROUTES]
    assert host_rows, "matrix must include host-serial rows"
    assert set(res.results) == set(want), (set(want) - set(res.results))
    for i in sorted(want):
        _results_equal(want[i], res.results[i], ctx=f"binding {i}")
    for i in host_rows:
        assert i not in res.results  # the serial fallback owns them


def test_parity_big_tier_chunked_vs_single_dispatch():
    """ROUTE_DEVICE_BIG rows (beyond the tier-1 compact caps) must take
    the big-lane sub-solve identically under chunking."""
    rng = random.Random(3)
    clusters = bench.build_fleet(rng, 560)  # pads to C=1024 > COMPACT_LANES
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()

    def big_binding(b):
        pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
        return (
            ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"a{b}",
                                         uid=f"u{b}"),
                # > COMPACT_DIVISION_CAP (64): tier-2 sub-solve
                replicas=80 + b,
                replica_requirements=ReplicaRequirements(resource_request={
                    "cpu": Quantity.from_milli(100)}),
                placement=pl,
            ),
            ResourceBindingStatus(),
        )

    def small_binding(b):
        pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED))
        return (
            ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"s{b}",
                                         uid=f"su{b}"),
                replicas=2, placement=pl),
            ResourceBindingStatus(),
        )

    items = [big_binding(0), small_binding(1), big_binding(2),
             small_binding(3), big_binding(4), small_binding(5)]
    want, batch = _single_dispatch_reference(items, cindex, est, waves=1)
    assert (batch.route == tensors.ROUTE_DEVICE_BIG).sum() == 3
    res = pipeline.run_pipeline(items, cindex, est, chunk=2, waves=1,
                                carry=True)
    assert set(res.results) == set(want)
    for i in sorted(want):
        _results_equal(want[i], res.results[i], ctx=f"binding {i}")

    # carry_spread=True (the scheduler's multi-chunk mode) routes the
    # carry-in through the big sub-batch vocabulary: every row must still
    # produce the same result CLASS (dynamic weights legitimately shift
    # individual tie-breaks once consumption is priced)
    res2 = pipeline.run_pipeline(items, cindex, est, chunk=2, waves=1,
                                 carry=True, carry_spread=True)
    assert set(res2.results) == set(want)
    for i in sorted(want):
        assert isinstance(res2.results[i], Exception) \
            == isinstance(want[i], Exception), i


def test_carry_sequential_equivalence_bit_identical():
    """Chunked execution at one binding per wave with chunk-to-chunk carry
    must equal ONE compact solve at one binding per wave: the carry
    transports the consumed-capacity state exactly (the executor analog of
    test_contention's cross-batch continuity)."""
    from karmada_tpu.ops.solver import solve_compact

    rng = random.Random(2)
    clusters = bench.build_fleet(rng, 32)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 64, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    b0 = tensors.encode_batch(items, cindex, est)
    dev_items = [items[i] for i in range(len(items))
                 if b0.route[i] == tensors.ROUTE_DEVICE][:32]
    assert len(dev_items) == 32

    batch = tensors.encode_batch(dev_items, cindex, est)
    i1, v1, s1, _ = solve_compact(batch, waves=len(dev_items))
    ref = tensors.decode_compact(batch, i1, v1, s1)

    res = pipeline.run_pipeline(dev_items, cindex, est, chunk=8, waves=8,
                                carry=True)
    for j in range(len(dev_items)):
        _results_equal(ref[j], res.results[j], ctx=f"binding {j}")


def _capacity_items():
    import sys

    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_contention import mk_binding, mk_cluster

    return mk_cluster, mk_binding


def test_chunk_carry_rejects_consumed_capacity():
    """Chunk k+1 must reject capacity chunk k consumed (and without carry,
    both chunks price the raw snapshot — the documented divergence)."""
    mk_cluster, mk_binding = _capacity_items()
    clusters = [mk_cluster("m1", cpu_milli=1000, mem_units=10**6,
                           pods=10**6)]
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()
    a = mk_binding(0, replicas=8, cpu_milli=100, mem_units=0)
    b = mk_binding(1, replicas=8, cpu_milli=100, mem_units=0)

    res = pipeline.run_pipeline([a, b], cindex, est, chunk=1, waves=1,
                                carry=True)
    assert not isinstance(res.results[0], Exception)
    assert isinstance(res.results[1], serial.UnschedulableError)

    res2 = pipeline.run_pipeline([a, b], cindex, est, chunk=1, waves=1,
                                 carry=False)
    assert not isinstance(res2.results[0], Exception)
    assert not isinstance(res2.results[1], Exception)


def test_chunk_carry_survives_vocabulary_change_and_gap():
    """The device-side carry chain must stay exact across a chunk whose
    encoding vocabulary grows (lossless device remap) and across one whose
    vocabulary DROPS a consumed resource (segment close through the keyed
    CarryState)."""
    mk_cluster, mk_binding = _capacity_items()
    est = GeneralEstimator()

    # growth: chunk 1 adds a memory class; cpu consumption must survive
    clusters = [mk_cluster("m1", cpu_milli=1000, mem_units=10**6,
                           pods=10**6)]
    cindex = tensors.ClusterIndex.build(clusters)
    a = mk_binding(0, replicas=8, cpu_milli=100, mem_units=0)
    c = mk_binding(2, replicas=1, cpu_milli=100, mem_units=1)
    b = mk_binding(1, replicas=8, cpu_milli=100, mem_units=0)
    res = pipeline.run_pipeline([a, c, b], cindex, est, chunk=1, waves=1,
                                carry=True)
    assert not isinstance(res.results[0], Exception)
    assert not isinstance(res.results[1], Exception)
    assert isinstance(res.results[2], serial.UnschedulableError)

    # gap: chunk 1's vocabulary has NO memory resource at all; chunk 0's
    # memory consumption must still reach chunk 2
    clusters2 = [mk_cluster("m1", cpu_milli=10**9, mem_units=10,
                            pods=10**6)]
    cindex2 = tensors.ClusterIndex.build(clusters2)

    def mem(bi, rep):
        return mk_binding(bi, replicas=rep, cpu_milli=10, mem_units=1)

    def cpu_only(bi, rep):
        s, st = mk_binding(bi, replicas=rep, cpu_milli=10, mem_units=0)
        s.replica_requirements.resource_request.pop("memory")
        return s, st

    res2 = pipeline.run_pipeline([mem(0, 8), cpu_only(1, 5), mem(2, 8)],
                                 cindex2, est, chunk=1, waves=1, carry=True)
    assert not isinstance(res2.results[0], Exception)
    assert not isinstance(res2.results[1], Exception)
    assert isinstance(res2.results[2], serial.UnschedulableError)


def test_spread_consumption_reaches_later_chunks():
    """carry_spread (the scheduler's multi-chunk mode): a spread binding's
    consumption in chunk k must reach the main solve of chunk k+2 (the
    documented one-chunk lag), so a cycle cannot overcommit a cluster
    across its chunks' spread sets."""
    mk_cluster, mk_binding = _capacity_items()
    cluster = mk_cluster("m1", cpu_milli=1000, mem_units=10**6, pods=10**6)
    cluster.spec.region = "r1"
    cindex = tensors.ClusterIndex.build([cluster])
    est = GeneralEstimator()

    spread_pl = Placement(
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_REGION,
                             min_groups=1, max_groups=1),
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=1, max_groups=1),
        ],
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
    sp_spec, sp_status = mk_binding(0, replicas=8, cpu_milli=100,
                                    mem_units=0)
    sp_spec.placement = spread_pl
    filler = mk_binding(1, replicas=1, cpu_milli=10, mem_units=0)
    late = mk_binding(2, replicas=8, cpu_milli=100, mem_units=0)

    items = [(sp_spec, sp_status), filler, late]
    batch = tensors.encode_batch(items, cindex, est)
    assert batch.route[0] == tensors.ROUTE_DEVICE_SPREAD
    res = pipeline.run_pipeline(items, cindex, est, chunk=1, waves=1,
                                carry=True, carry_spread=True)
    assert not isinstance(res.results[0], Exception)  # spread: 8 x 100m
    assert not isinstance(res.results[1], Exception)
    # chunk 2 wants 800m; the spread binding already took 800 of 1000
    assert isinstance(res.results[2], serial.UnschedulableError)

    # without carry_spread the spread consumption is invisible: chunk 2
    # fits against the raw snapshot (the pre-pipeline per-chunk behavior)
    res2 = pipeline.run_pipeline(items, cindex, est, chunk=1, waves=1,
                                 carry=True, carry_spread=False)
    assert not isinstance(res2.results[2], Exception)


def test_cancelled_cycle_writes_nothing():
    """The degradation guard's event gates every stage boundary and every
    shared-state write: a pre-cancelled cycle runs nothing, and a cycle
    cancelled after chunk 0 finalizes abandons chunks 1+ (no results, no
    chunk metrics)."""
    clusters, cindex = _fleet(16)
    rng = random.Random(0)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 24, placements)
    est = GeneralEstimator()

    ev = threading.Event()
    ev.set()
    before = sm.PIPELINE_CHUNKS.value(carry="on")
    res = pipeline.run_pipeline(items, cindex, est, chunk=8, waves=2,
                                carry=True, cancelled=ev)
    assert res.results == {} and res.chunks == 0 and res.cancelled
    assert sm.PIPELINE_CHUNKS.value(carry="on") == before

    ev2 = threading.Event()
    finalized = []

    def on_chunk(st):
        finalized.append(st.index)
        ev2.set()  # the guard fires while chunk 1 is in flight

    before = sm.PIPELINE_CHUNKS.value(carry="on")
    res2 = pipeline.run_pipeline(items, cindex, est, chunk=8, waves=2,
                                 carry=True, cancelled=ev2,
                                 on_chunk=on_chunk)
    assert res2.cancelled
    assert finalized == [0] and res2.chunks == 1
    # nothing past chunk 0 escaped
    assert all(i < 8 for i in res2.results)
    assert sm.PIPELINE_CHUNKS.value(carry="on") == before + 1


def test_pipeline_smoke_bench_mix():
    """Fast no-device smoke (CI satellite): 3+ chunks of the bench mix,
    waves >= 2, through BOTH the executor and bench.run_batched (which
    must drive the same loop), with per-stage metrics observable."""
    rng = random.Random(0)
    clusters = bench.build_fleet(rng, 24)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 40, placements)
    est = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)

    chunks_before = sm.PIPELINE_CHUNKS.value(carry="on")
    stats = []
    res = pipeline.run_pipeline(items, cindex, est, chunk=16, waves=2,
                                carry=True, on_chunk=stats.append)
    assert res.chunks == 3 and len(stats) == 3
    assert sm.PIPELINE_CHUNKS.value(carry="on") == chunks_before + 3
    batch = tensors.encode_batch(items, cindex, est)
    n_device_owned = sum(1 for r in batch.route
                         if r in pipeline.DEVICE_ROUTES)
    assert res.scheduled + sum(res.failures.values()) == n_device_owned
    assert set(res.results) == {i for i in range(len(items))
                                if batch.route[i] in pipeline.DEVICE_ROUTES}
    for st in stats:
        assert st.own_s > 0 and st.wall_s > 0 and st.n > 0
    # chunk spans reached the metrics registry
    dump = sm.REGISTRY.dump()
    assert "karmada_scheduler_pipeline_chunk_duration_seconds" in dump

    # bench.run_batched is a thin wrapper over the same executor
    elapsed, solve_s, scheduled, lat, wall, failures = bench.run_batched(
        items, cindex, est, 16, tensors.EncoderCache(), waves=2)
    assert scheduled == res.scheduled and failures == res.failures
    assert len(lat) == 3 and len(wall) == 3 and solve_s > 0


def test_scheduler_service_uses_pipelined_executor():
    """_solve_device drives scheduler/pipeline: a cycle larger than
    pipeline_chunk splits into carried chunks and every binding still
    schedules (end to end through the ControlPlane)."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.policy import (
        PropagationPolicy,
        PropagationSpec,
        ResourceSelector,
    )
    from karmada_tpu.models.work import ResourceBinding

    from karmada_tpu.models.cluster import Cluster

    cp = ControlPlane(backend="device", pipeline_chunk=4)
    for i in range(3):
        cp.add_member(f"m{i}", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version=GVK[0],
                                                 kind=GVK[1])],
            placement=Placement())))
    for i in range(12):
        cp.apply({"apiVersion": GVK[0], "kind": GVK[1],
                  "metadata": {"namespace": "default", "name": f"d{i}"},
                  "spec": {"replicas": 2}})
    cp.tick()
    rbs = cp.store.list(ResourceBinding.KIND)
    assert len(rbs) == 12
    assert all(rb.spec.clusters for rb in rbs)

    # a cycle wider than pipeline_chunk runs as carried chunks: drive
    # _solve_device directly so the chunk split is deterministic
    clusters = list(cp.store.list(Cluster.KIND))
    items = [(rb.spec, rb.status) for rb in rbs]
    chunks_before = sm.PIPELINE_CHUNKS.value(carry="on")
    out = cp.scheduler._solve_device(items, clusters)  # noqa: SLF001
    assert len(out) == 12
    assert sm.PIPELINE_CHUNKS.value(carry="on") == chunks_before + 3
