"""Fused whole-cycle-on-device steady state (ops/resident_gather).

The fused path keeps the binding-axis slot store device-resident and
gathers each cycle's batch rows ON device, so a warm cycle is: scatter
watch deltas into the mirrors -> jitted gather of the pending rows ->
solve with operands already placed -> d2h only the compact COO.  The
host assemble stays the behavior-defining control, and everything here
is parity against it:

  * bit-exactness: a fused batch's binding-axis planes equal the host
    control's on EVERY row (padding included), dtypes included;
  * parity fuzz through the real pipelined executor across churn
    patterns — capacity-only cluster deltas, binding churn, vocabulary
    growth (new resource / placement / class mid-run), a structural
    bump forcing the host fallback, and mixed routes incl. the big
    lane tier;
  * transfer accounting: a warm fused cycle ships ZERO binding-axis
    fields host->device (karmada_solver_h2d_binding_fields_total flat);
  * donation safety: the carry chain's donated dispatches never
    invalidate the resident mirrors — they stay live and the next
    fused cycle is bit-exact;
  * fallbacks: explain-armed chunks and structural rebuilds take the
    host control (counted), then the plane returns to fused;
  * AOT: variants_for(fused=True) includes the fused-gather executable
    and warm_executables pre-compiles it per pow2 batch shape
    (satellite: the first fused cycle mid-soak must not eat a compile);
  * vet: the spec-coverage pass catches slot-store/gather-kernel/spec-
    table drift on seeded fixtures (the drift class this path creates);
  * mesh: fused-vs-host parity under an active 2-device mesh (8-device
    and heavy-churn legs are `slow`).
"""

from __future__ import annotations

import copy
import dataclasses
import random
import sys
import textwrap

import numpy as np
import pytest

import bench
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.ops import meshing, tensors
from karmada_tpu.ops import resident_gather
from karmada_tpu.ops.solver import DONATED_DISPATCHES, H2D_BINDING_FIELDS
from karmada_tpu.resident import ResidentState, RowToken, compare_batches
from karmada_tpu.scheduler import pipeline

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_pipeline_executor import _mixed_items, _results_equal  # noqa: E402

pytestmark = pytest.mark.fused

BINDING_PLANES = (
    "b_valid", "placement_id", "gvk_id", "class_id", "replicas",
    "uid_desc", "fresh", "non_workload", "nw_shortcut",
    "prev_idx", "prev_val", "evict_idx",
)


@pytest.fixture(autouse=True)
def _no_mesh_leak():
    yield
    meshing.deactivate()


class Fleet:
    """A mutable (clusters, items) world with resourceVersion ledger and
    a fused + host-control ResidentState pair driven in lockstep."""

    def __init__(self, nc=24, n=64, seed=0, audit=0):
        self.rng = random.Random(seed)
        self.clusters = bench.build_fleet(self.rng, nc)
        placements = bench.build_placements(
            self.rng, [c.name for c in self.clusters])
        self.items = bench.build_bindings(self.rng, n, placements)
        self.n = n
        self.rvs = [1] * n
        self.est = GeneralEstimator()
        self.fused = ResidentState(estimator=self.est, audit_interval=audit,
                                   fused=True)
        self.host = ResidentState(estimator=self.est, audit_interval=audit,
                                  fused=False)

    def tokens(self, state):
        pfx = "f" if state is self.fused else "h"
        return [RowToken(f"{pfx}/{i}", self.rvs[i]) for i in range(self.n)]

    def adopt(self):
        for state in (self.fused, self.host):
            state.begin_cycle(self.clusters)
            state.encode_cycle(self.items, self.tokens(state))

    def cycle(self, state, chunk=32, waves=4, explain=None):
        state.begin_cycle(self.clusters)
        toks = self.tokens(state)

        def encode(part, offset, armed):
            return state.encode_cycle(
                part, toks[offset:offset + len(part)], explain=armed)

        return pipeline.run_pipeline(
            self.items, state.cindex, self.est, chunk=chunk, waves=waves,
            cache=state.enc_cache, carry=True, carry_spread=True,
            encode=encode, explain=explain)

    def assert_parity(self, chunk=32, ctx=""):
        rf = self.cycle(self.fused, chunk=chunk)
        rh = self.cycle(self.host, chunk=chunk)
        assert set(rf.results) == set(rh.results), ctx
        for i in sorted(rf.results):
            _results_equal(rh.results[i], rf.results[i],
                           ctx=f"{ctx} binding {i}")
        return rf, rh

    def churn_bindings(self, idx):
        for i in idx:
            spec, status = self.items[i]
            self.items[i] = (
                dataclasses.replace(spec, replicas=spec.replicas + 1),
                status)
            self.rvs[i] += 1

    def churn_capacity(self, k):
        from karmada_tpu.utils.quantity import Quantity

        for lane in self.rng.sample(range(len(self.clusters)), k):
            c = copy.deepcopy(self.clusters[lane])
            c.metadata.resource_version += 1
            rs = c.status.resource_summary
            if rs is not None and "cpu" in rs.allocated:
                rs.allocated["cpu"] = Quantity.from_milli(
                    rs.allocated["cpu"].milli_value() + 100)
            self.clusters[lane] = c


def _encode_pair(fleet):
    """One encode_cycle on each state over the full item list; returns
    (fused batch, host batch)."""
    bf = bh = None
    for state in (fleet.fused, fleet.host):
        state.begin_cycle(fleet.clusters)
        b = state.encode_cycle(fleet.items, fleet.tokens(state))
        if state is fleet.fused:
            bf = b
        else:
            bh = b
    return bf, bh


# -- bit-exactness of the gather itself ---------------------------------------


def test_fused_batch_bit_exact_vs_host_assemble():
    fleet = Fleet(nc=16, n=40)
    fleet.adopt()
    bf, bh = _encode_pair(fleet)
    assert bf.fused and not bh.fused
    for f in BINDING_PLANES:
        a, b = np.asarray(getattr(bf, f)), np.asarray(getattr(bh, f))
        assert a.dtype == b.dtype, f
        assert np.array_equal(a, b), f
    # host-side companions: route identical, cluster fields shared
    assert np.array_equal(bf.route, bh.route)
    assert isinstance(bf.route, np.ndarray)
    for f in ("avail_milli", "pl_mask", "name_rank"):
        assert getattr(bf, f) is getattr(fleet.fused.plane, f)
    # the donation-safety hint equals the solver's own host-side bound
    from karmada_tpu.ops.solver import _nnz_bound

    assert bf.nnz_bound_hint == _nnz_bound(bh)
    # and the fused batch passes the plane's own bit-exact audit
    assert compare_batches(
        bf, tensors.encode_batch(fleet.items, fleet.fused.cindex,
                                 fleet.est)) == []


def test_fused_zero_binding_field_h2d():
    fleet = Fleet(nc=16, n=48)
    fleet.adopt()
    fleet.cycle(fleet.fused)  # warm the signatures
    h0 = H2D_BINDING_FIELDS.value()
    res = fleet.cycle(fleet.fused)
    assert res.scheduled > 0
    assert H2D_BINDING_FIELDS.value() - h0 == 0, \
        "a warm fused cycle must ship zero binding-axis fields h2d"
    h1 = H2D_BINDING_FIELDS.value()
    fleet.cycle(fleet.host)
    assert H2D_BINDING_FIELDS.value() - h1 > 0, \
        "the host control path must be the one paying the uploads"


# -- parity fuzz across churn patterns ----------------------------------------


def test_fused_parity_capacity_only_deltas():
    fleet = Fleet(nc=24, n=64, seed=1)
    fleet.adopt()
    for cyc in range(3):
        fleet.churn_capacity(3)
        rf, _ = fleet.assert_parity(ctx=f"capacity cycle {cyc}")
        assert rf.scheduled > 0
    st = fleet.fused.stats()
    assert st["rebuilds"] == {"init": 1}
    assert st["fused"]["cycles"] >= 3
    assert st["fused"]["fallbacks"] == {}


def test_fused_parity_binding_churn_and_vocab_growth():
    from karmada_tpu.models.policy import (
        REPLICA_SCHEDULING_DUPLICATED,
        Placement,
        ReplicaSchedulingStrategy,
    )
    from karmada_tpu.models.work import (
        ObjectReference,
        ReplicaRequirements,
        ResourceBindingSpec,
        ResourceBindingStatus,
    )
    from karmada_tpu.utils.quantity import Quantity

    fleet = Fleet(nc=24, n=64, seed=2)
    fleet.adopt()
    fleet.churn_bindings(fleet.rng.sample(range(fleet.n), 9))
    fleet.assert_parity(ctx="binding churn")
    # vocabulary growth: a brand-new placement AND resource class lands
    # mid-run (grows the placement/class/resource axes; cluster-side
    # masters re-place, slot rows scatter)
    gpu = (ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 namespace="d", name="gpu-new",
                                 uid="uid-gpu-new"),
        replicas=2,
        replica_requirements=ReplicaRequirements(resource_request={
            "nvidia.com/gpu": Quantity.from_units(1),
            "cpu": Quantity.from_milli(111)}),
        placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
    ), ResourceBindingStatus())
    fleet.items.append(gpu)
    fleet.rvs.append(1)
    fleet.n += 1
    fleet.assert_parity(ctx="vocab growth")
    bf, bh = _encode_pair(fleet)
    assert bf.fused
    assert "nvidia.com/gpu" in bf.res_names
    for f in BINDING_PLANES:
        assert np.array_equal(np.asarray(getattr(bf, f)),
                              np.asarray(getattr(bh, f))), f


def test_fused_structural_bump_forces_host_fallback_then_recovers():
    fleet = Fleet(nc=12, n=32, seed=3)
    fleet.adopt()
    fleet.assert_parity(ctx="pre-bump")
    # structural churn: a new cluster joins -> membership rebuild; the
    # rebuild cycle is ONE full host encode (the lossless fallback), the
    # next cycle gathers fused again
    rng = random.Random(99)
    fleet.clusters = fleet.clusters + bench.build_fleet(rng, 13)[-1:]
    bf, bh = _encode_pair(fleet)
    assert not bf.fused, "the rebuild cycle must take the host control"
    assert fleet.fused.stats()["rebuilds"].get("membership") == 1
    fleet.assert_parity(ctx="post-bump")
    bf2, _ = _encode_pair(fleet)
    assert bf2.fused, "the plane must return to the fused path"


def test_fused_parity_mixed_routes():
    """The route matrix (device / region-spread / host-serial rows):
    fused cycles only own DEVICE_ROUTES rows, exactly like the host."""
    rng = random.Random(5)
    clusters = bench.build_fleet(rng, 12)
    items = _mixed_items()
    n = len(items)
    est = GeneralEstimator()
    states = {}
    results = {}
    for name, fused in (("fused", True), ("host", False)):
        state = ResidentState(estimator=est, audit_interval=0, fused=fused)
        state.begin_cycle(clusters)
        toks = [RowToken(f"{name}/{i}", 1) for i in range(n)]
        state.encode_cycle(items, toks)
        state.begin_cycle(clusters)

        def encode(part, offset, armed, _s=state, _t=toks):
            return _s.encode_cycle(part, _t[offset:offset + len(part)],
                                   explain=armed)

        results[name] = pipeline.run_pipeline(
            items, state.cindex, est, chunk=3, waves=2, carry=True,
            carry_spread=True, cache=state.enc_cache, encode=encode)
        states[name] = state
    assert set(results["fused"].results) == set(results["host"].results)
    for i in sorted(results["fused"].results):
        _results_equal(results["host"].results[i],
                       results["fused"].results[i], ctx=f"binding {i}")
    assert states["fused"].stats()["fused"]["cycles"] > 0


def test_fused_parity_big_tier():
    """ROUTE_DEVICE_BIG rows (beyond the tier-1 compact caps) through the
    fused gather: the big sub-solve re-encodes its sub-batch on host
    either way; the main-path rows must still gather fused."""
    from karmada_tpu.models.policy import (
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        REPLICA_DIVISION_WEIGHTED,
        REPLICA_SCHEDULING_DIVIDED,
        REPLICA_SCHEDULING_DUPLICATED,
        ClusterPreferences,
        Placement,
        ReplicaSchedulingStrategy,
    )
    from karmada_tpu.models.work import (
        ObjectReference,
        ReplicaRequirements,
        ResourceBindingSpec,
        ResourceBindingStatus,
    )
    from karmada_tpu.utils.quantity import Quantity

    rng = random.Random(7)
    clusters = bench.build_fleet(rng, 560)  # pads past COMPACT_LANES

    def binding(b, big):
        if big:
            pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
        else:
            pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED))
        return (ResourceBindingSpec(
            resource=ObjectReference(api_version="apps/v1",
                                     kind="Deployment", namespace="d",
                                     name=f"a{b}", uid=f"u{b}"),
            replicas=(80 + b) if big else 2,
            replica_requirements=ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(100)}),
            placement=pl), ResourceBindingStatus())

    items = [binding(b, big=b % 2 == 0) for b in range(6)]
    est = GeneralEstimator()
    out = {}
    for name, fused in (("fused", True), ("host", False)):
        state = ResidentState(estimator=est, audit_interval=0, fused=fused)
        state.begin_cycle(clusters)
        toks = [RowToken(f"{name}/{i}", 1) for i in range(len(items))]
        state.encode_cycle(items, toks)
        state.begin_cycle(clusters)

        def encode(part, offset, armed, _s=state, _t=toks):
            return _s.encode_cycle(part, _t[offset:offset + len(part)],
                                   explain=armed)

        out[name] = pipeline.run_pipeline(
            items, state.cindex, est, chunk=3, waves=1, carry=True,
            carry_spread=True, cache=state.enc_cache, encode=encode)
        if fused:
            assert state.stats()["fused"]["cycles"] > 0
    assert set(out["fused"].results) == set(out["host"].results)
    for i in sorted(out["fused"].results):
        _results_equal(out["host"].results[i], out["fused"].results[i],
                       ctx=f"binding {i}")


# -- donation safety ----------------------------------------------------------


def test_fused_donation_never_invalidates_mirrors():
    """Multi-chunk fused cycles run the donated carry chain; the resident
    mirrors (slot store AND cluster plane) must stay live through it —
    donation only ever consumes the used0 accumulators — and the next
    fused cycle must still be bit-exact."""
    fleet = Fleet(nc=16, n=96, seed=8)
    fleet.adopt()
    d0 = DONATED_DISPATCHES.value()
    fleet.assert_parity(chunk=24, ctx="donated chain")
    assert DONATED_DISPATCHES.value() > d0, \
        "the multi-chunk fused cycle must engage the donated dispatch"
    for f, m in fleet.fused.device_rows.mirrors.items():
        deleted = getattr(m, "is_deleted", None)
        assert not (deleted is not None and deleted()), \
            f"slot mirror {f} was consumed by donation"
    # churn + another donated cycle: scatter-advanced mirrors still exact
    fleet.churn_bindings(fleet.rng.sample(range(fleet.n), 7))
    fleet.assert_parity(chunk=24, ctx="donated chain after churn")
    bf, _ = _encode_pair(fleet)
    assert compare_batches(
        bf, tensors.encode_batch(fleet.items, fleet.fused.cindex,
                                 fleet.est)) == []


# -- fallbacks ----------------------------------------------------------------


def test_fused_explain_chunks_fall_back_to_host():
    from karmada_tpu.obs import decisions as obs_decisions

    fleet = Fleet(nc=12, n=24, seed=9)
    fleet.adopt()
    rec = obs_decisions.DecisionRecorder()
    res = fleet.cycle(fleet.fused, explain=rec)
    assert res.scheduled > 0
    st = fleet.fused.stats()["fused"]
    assert st["fallbacks"].get("explain", 0) > 0
    # and the decisions actually recorded (the host control owns explain)
    assert len(rec.recent()) > 0
    # a plain cycle afterwards goes fused again
    bf, _ = _encode_pair(fleet)
    assert bf.fused


def test_fused_broken_device_rows_degrade_to_host():
    fleet = Fleet(nc=12, n=24, seed=10)
    fleet.adopt()
    fleet.fused.device_rows.broken = True
    bf, bh = _encode_pair(fleet)
    assert not bf.fused
    st = fleet.fused.stats()["fused"]
    assert st["fallbacks"].get("device-rows", 0) > 0
    assert not st["available"]
    for f in BINDING_PLANES:
        assert np.array_equal(np.asarray(getattr(bf, f)),
                              np.asarray(getattr(bh, f))), f


# -- AOT warm (satellite 1) ---------------------------------------------------


def test_variants_for_includes_fused():
    from karmada_tpu.ops import aotcache

    assert aotcache.variants_for(0.0, False) == ("plain",)
    assert aotcache.variants_for(0.0, False, fused=True) == \
        ("plain", "fused")
    assert aotcache.variants_for(0.5, True, fused=True)[-1] == "fused"


def test_warm_executables_compiles_fused_gather():
    from karmada_tpu.ops import aotcache

    rng = random.Random(11)
    clusters = bench.build_fleet(rng, 8)
    try:
        res = aotcache.warm_executables(
            clusters, GeneralEstimator(), shapes=(8,),
            variants=(aotcache.VARIANT_FUSED,), resident_cap=64)
        assert res["_totals"]["compiled"] == 1
        entry = res["B8xS64:fused"]
        assert entry["slot_cap"] == 64 and entry["compile_s"] >= 0
        ledger = aotcache.state_payload()["warmup"]
        assert ledger.get("B8xS64:fused", {}).get("state") == "done"
        # warming is real: the warmed signature dispatches without a
        # fresh trace (same (B, cap, Kp, Ke) geometry)
        timings2 = resident_gather.aot_warm(8, cap=64)
        assert timings2["compile_s"] < 1.0
    finally:
        # the warm ledger is process-wide and other suites assert its
        # exact contents (test_coldstart): drop this test's entry
        aotcache._STATE["warmup"].pop("B8xS64:fused", None)  # noqa: SLF001


def test_scheduler_plumbs_resident_fused():
    from karmada_tpu.scheduler.service import Scheduler
    from karmada_tpu.store.store import ObjectStore
    from karmada_tpu.store.worker import Runtime

    sched = Scheduler(ObjectStore(), Runtime(), backend="device",
                      resident=True, resident_fused=True)
    assert sched.resident_fused
    assert sched._resident is not None and sched._resident.fused
    # degrade + re-arm keeps the fused configuration
    assert sched._resident_cfg[2] is True


# -- vet drift fixtures (satellite 4) -----------------------------------------


def _vet(tmp_path, files):
    from karmada_tpu.analysis.vet import run_vet

    for fname, src in files.items():
        (tmp_path / fname).write_text(textwrap.dedent(src))
    return run_vet([str(tmp_path)], rules=["spec-coverage"])


_MESHING_OK = """
    HOST_ONLY_FIELDS = frozenset({"route"})

    def shard_specs():
        return {"placement_id": 1, "replicas": 2, "b_valid": 3}
"""


def test_vet_catches_uncovered_slot_store_field(tmp_path):
    report = _vet(tmp_path, {
        "meshing.py": _MESHING_OK,
        "state.py": """
            BINDING_SLOT_FIELDS = ("placement_id", "replicas", "route")
            DEVICE_SLOT_FIELDS = BINDING_SLOT_FIELDS + ("secret_rows",)
        """,
    })
    msgs = [f.message for f in report.findings]
    assert any("slot-store field `secret_rows`" in m for m in msgs), msgs


def test_vet_catches_slot_vs_gather_drift(tmp_path):
    report = _vet(tmp_path, {
        "meshing.py": _MESHING_OK,
        "state.py": """
            BINDING_SLOT_FIELDS = ("placement_id", "replicas", "route")
            DEVICE_SLOT_FIELDS = BINDING_SLOT_FIELDS
        """,
        "resident_gather.py": """
            GATHER_FIELDS = ("placement_id", "route")
            OUT_FIELDS = ("b_valid", "placement_id")
        """,
    })
    msgs = [f.message for f in report.findings]
    assert any("`replicas`" in m and "slot store but not the gather" in m
               for m in msgs), msgs


def test_vet_catches_unchained_gather_output(tmp_path):
    report = _vet(tmp_path, {
        "meshing.py": _MESHING_OK,
        "resident_gather.py": """
            GATHER_FIELDS = ("placement_id", "replicas", "route")
            OUT_FIELDS = ("b_valid", "placement_id", "replicas", "mystery")
        """,
    })
    msgs = [f.message for f in report.findings]
    assert any("fused-gather output `mystery`" in m for m in msgs), msgs


def test_vet_clean_on_real_tree_tables():
    """The shipped tables are drift-free: slot store == gather kernel,
    every output chained (this is the live gate, not a fixture)."""
    from karmada_tpu.resident.state import DEVICE_SLOT_FIELDS

    assert DEVICE_SLOT_FIELDS == resident_gather.GATHER_FIELDS
    keys = set(meshing.shard_specs())
    assert set(resident_gather.OUT_FIELDS) <= keys
    assert set(DEVICE_SLOT_FIELDS) - {"route"} <= keys
    assert "route" in meshing.HOST_ONLY_FIELDS


# -- mesh legs ----------------------------------------------------------------


def _mesh_parity(shape):
    import jax

    devs = jax.devices()
    need = shape[0] * shape[1]
    if len(devs) < need:
        pytest.skip(f"needs {need} virtual devices")
    meshing.activate(shape, devs[:need])
    try:
        fleet = Fleet(nc=16, n=64, seed=12)
        fleet.adopt()
        fleet.churn_bindings(fleet.rng.sample(range(fleet.n), 5))
        rf, rh = fleet.assert_parity(chunk=16, ctx=f"mesh {shape}")
        assert rf.scheduled == rh.scheduled > 0
        # the gather's out-shardings ARE the solver's in-shardings: one
        # fused batch's device plane must carry the spec-table sharding
        bf, _ = _encode_pair(fleet)
        assert bf.fused
        plan = meshing.active()
        want = meshing.sharding_for(plan.mesh, "replicas",
                                    np.asarray(bf.replicas).shape)
        assert bf.replicas.sharding.is_equivalent_to(
            want, np.asarray(bf.replicas).ndim)
    finally:
        meshing.deactivate()


def test_fused_mesh_parity_two_devices():
    _mesh_parity((1, 2))


@pytest.mark.slow
def test_fused_mesh_parity_eight_devices():
    _mesh_parity((2, 4))


@pytest.mark.slow
def test_fused_heavy_churn_fuzz():
    """Long mixed-churn fuzz: interleaved capacity deltas, binding churn,
    vocabulary growth and membership bumps over many cycles, parity
    asserted every cycle, closing audit bit-exact."""
    fleet = Fleet(nc=32, n=128, seed=13, audit=2)
    fleet.adopt()
    for cyc in range(8):
        action = cyc % 4
        if action == 0:
            fleet.churn_capacity(4)
        elif action == 1:
            fleet.churn_bindings(
                fleet.rng.sample(range(fleet.n), fleet.n // 8))
        elif action == 2:
            fleet.churn_capacity(2)
            fleet.churn_bindings(fleet.rng.sample(range(fleet.n), 3))
        else:
            rng = random.Random(1000 + cyc)
            fleet.clusters = fleet.clusters + \
                bench.build_fleet(rng, 33 + cyc)[-1:]
        fleet.assert_parity(chunk=32, ctx=f"fuzz cycle {cyc}")
    bf, _ = _encode_pair(fleet)
    assert compare_batches(
        bf, tensors.encode_batch(fleet.items, fleet.fused.cindex,
                                 fleet.est)) == []
    st = fleet.fused.stats()
    assert st["audits"]["mismatch"] == 0 and st["audits"]["ok"] > 0
