"""Explain plane (obs/decisions + ops/solver explain jit variant).

Covers the ISSUE-5 acceptance surface: serial-vs-batched verdict parity
on fixtures exercising every filter stage (incl. out-of-tree plugin
filters and cluster-spread elimination), unschedulable dominant-reason
classification into the queue + metrics, decision-ring retention /
eviction, the disarmed path compiling nothing new and recording nothing,
and the HTTP + `karmadactl explain` render smoke.
"""

import json
import urllib.error
import urllib.request

import pytest

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
    Taint,
)
from karmada_tpu.models.meta import LabelSelector, ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    GracefulEvictionTask,
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.obs import decisions as dec
from karmada_tpu.ops import serial, tensors
from karmada_tpu.ops.solver import _jit_cache_size, solve_compact
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


def mk_cluster(name, cpu_milli=64_000, pods=100, labels=None, taints=(),
               api=True, provider="aws", region="us", deleting=False):
    meta = ObjectMeta(name=name, labels=dict(labels or {"tier": "gold"}))
    if deleting:
        meta.deletion_timestamp = 1.0
    return Cluster(
        metadata=meta,
        spec=ClusterSpec(region=region, provider=provider,
                         taints=list(taints)),
        status=ClusterStatus(
            api_enablements=([APIEnablement(GVK[0], [GVK[1]])] if api
                             else []),
            resource_summary=ResourceSummary(
                allocatable={"cpu": Quantity.from_milli(cpu_milli),
                             "pods": Quantity.from_units(pods)},
                allocated={},
            ),
        ),
    )


def dyn_strategy():
    return ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
        replica_division_preference=REPLICA_DIVISION_WEIGHTED,
        weight_preference=ClusterPreferences(
            dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
    )


def mk_spec(placement, name="app", replicas=5, evict_from=()):
    return ResourceBindingSpec(
        resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                 namespace="default", name=name,
                                 uid=f"uid-{name}"),
        replicas=replicas,
        replica_requirements=ReplicaRequirements(resource_request={
            "cpu": Quantity.from_milli(100)}),
        placement=placement,
        graceful_eviction_tasks=[GracefulEvictionTask(from_cluster=c)
                                 for c in evict_from],
    )


@pytest.fixture(autouse=True)
def _clean_global_ring():
    yield
    dec.disable()


# ---------------------------------------------------------------------------
# serial-vs-batched verdict parity
# ---------------------------------------------------------------------------


def test_verdict_bits_match_serial_reference_every_stage():
    """Every filter stage exercised at once: for each rejected cluster
    the device mask's LOWEST set bit names exactly the reason the serial
    first-rejection-wins diagnosis reports; feasible clusters carry no
    filter bit."""
    from karmada_tpu.scheduler.plugins import REGISTRY as PLUGINS

    clusters = [
        mk_cluster("m-ok1"),
        mk_cluster("m-ok2"),
        mk_cluster("m-ok3"),
        mk_cluster("m-noapi", api=False),
        mk_cluster("m-taint", taints=[Taint(key="dedicated", value="infra",
                                            effect="NoSchedule")]),
        mk_cluster("m-aff", labels={"tier": "silver"}),
        mk_cluster("m-noprov", provider=""),
        mk_cluster("m-evict"),
        mk_cluster("m-plug"),
        mk_cluster("m-del", deleting=True),
    ]
    placement = Placement(
        cluster_affinity=ClusterAffinity(
            label_selector=LabelSelector(match_labels={"tier": "gold"})),
        spread_constraints=[
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                             min_groups=1, max_groups=2),
            # provider alongside cluster: filters only, stays on device
            SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_PROVIDER,
                             min_groups=1, max_groups=2),
        ],
        replica_scheduling=dyn_strategy(),
    )
    spec = mk_spec(placement, evict_from=["m-evict"])
    items = [(spec, ResourceBindingStatus())]
    PLUGINS.register_filter(
        "testPlug", lambda pl, c: "plugin rejected this cluster"
        if c.name == "m-plug" else None)
    try:
        cindex = tensors.ClusterIndex.build(clusters)
        batch = tensors.encode_batch(items, cindex, explain=True)
        assert batch.route[0] == tensors.ROUTE_DEVICE
        res = solve_compact(batch, waves=1, explain=True)
        verdict = res[-1][0]

        feasible, diagnosis = serial.find_clusters_that_fit(
            spec, ResourceBindingStatus(), clusters)
        feasible_names = {c.name for c in feasible}
        assert feasible_names == {"m-ok1", "m-ok2", "m-ok3"}
        # every stage is present in the serial diagnosis
        assert {dec.VERDICT_BIT_NAMES[dec.bit_for_serial_reason(m)]
                for m in diagnosis.values()} == {
            "api_enablement", "toleration", "affinity", "spread_property",
            "eviction", "plugin_filter"}
        for i, c in enumerate(clusters):
            mask = int(verdict[0][i])
            if c.metadata.deleting:
                assert mask & dec.VERDICT_CLUSTER_GONE
                continue
            if c.name in diagnosis:
                want = dec.VERDICT_BIT_NAMES[
                    dec.bit_for_serial_reason(diagnosis[c.name])]
                assert dec.first_reason(mask) == want, (
                    c.name, dec.reasons_of(mask), diagnosis[c.name])
            else:
                assert mask & dec.VERDICT_FILTER_MASK == 0, (
                    c.name, dec.reasons_of(mask))
    finally:
        PLUGINS.unregister("testPlug")


def test_cluster_spread_elimination_marks_not_selected():
    """max_groups=2 over 3 feasible clusters: the eliminated cluster is
    feasible (no filter bits) but carries NOT_SELECTED — "which spread
    constraint ate its replicas"."""
    clusters = [mk_cluster(f"m{i}") for i in range(3)]
    placement = Placement(
        spread_constraints=[SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=1, max_groups=2)],
        replica_scheduling=dyn_strategy(),
    )
    items = [(mk_spec(placement, replicas=6), ResourceBindingStatus())]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, explain=True)
    res = solve_compact(batch, waves=1, explain=True)
    verdict, _score, _avail, outcome = res[-1]
    status, _ = dec.split_outcome(int(outcome[0]))
    assert status == tensors.STATUS_OK
    masks = {c.name: int(verdict[0][i]) for i, c in enumerate(clusters)}
    eliminated = [n for n, m in masks.items() if m & dec.VERDICT_NOT_SELECTED]
    assert len(eliminated) == 1
    for m in masks.values():
        assert m & dec.VERDICT_FILTER_MASK == 0
    # parity: the serial path selects the same two and drops the same one
    decoded = tensors.decode_compact(batch, res[0], res[1], res[2],
                                     items=items)
    assert {t.name for t in decoded[0]} == set(masks) - set(eliminated)


def test_unschedulable_dominant_reason_is_capacity():
    clusters = [mk_cluster("m-a", cpu_milli=0), mk_cluster("m-b", cpu_milli=0)]
    placement = Placement(replica_scheduling=dyn_strategy())
    items = [(mk_spec(placement, replicas=50), ResourceBindingStatus())]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, explain=True)
    res = solve_compact(batch, waves=1, explain=True)
    _verdict, _s, _a, outcome = res[-1]
    status, reason = dec.split_outcome(int(outcome[0]))
    assert status == tensors.STATUS_UNSCHEDULABLE
    assert reason == "capacity"


def test_fit_error_dominant_reason_is_the_majority_stage():
    taint = Taint(key="dedicated", value="infra", effect="NoSchedule")
    clusters = [
        mk_cluster("m-t1", taints=[taint]),
        mk_cluster("m-t2", taints=[taint]),
        mk_cluster("m-t3", taints=[taint]),
        mk_cluster("m-noapi", api=False),
    ]
    placement = Placement(replica_scheduling=dyn_strategy())
    items = [(mk_spec(placement), ResourceBindingStatus())]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, explain=True)
    res = solve_compact(batch, waves=1, explain=True)
    outcome = res[-1][3]
    status, reason = dec.split_outcome(int(outcome[0]))
    assert status == tensors.STATUS_FIT_ERROR
    assert reason == "toleration"  # 3 untolerated vs 1 missing API


# ---------------------------------------------------------------------------
# decision ring
# ---------------------------------------------------------------------------


def test_decision_ring_retention_and_unschedulable_shelf():
    ring = dec.DecisionRecorder(capacity=4, unsched_keep=2)
    for i in range(6):
        ring.record({"key": f"ns/ok-{i}", "outcome": "scheduled",
                     "reason": None})
    assert ring.dropped == 2
    assert len(ring.recent()) == 4
    assert ring.get("ns/ok-5")["key"] == "ns/ok-5"
    assert ring.get("ns/ok-0") is None  # evicted, not on any shelf
    for i in range(3):
        ring.record({"key": f"ns/bad-{i}", "outcome": "unschedulable",
                     "reason": "capacity"})
    # the shelf keeps the LATEST failed decisions, bounded to 2
    shelf = ring.unschedulable()
    assert [d["key"] for d in shelf] == ["ns/bad-2", "ns/bad-1"]
    # a shelved decision survives ring eviction
    for i in range(10):
        ring.record({"key": f"ns/flood-{i}", "outcome": "scheduled",
                     "reason": None})
    assert ring.get("ns/bad-2")["outcome"] == "unschedulable"
    stats = ring.stats()
    assert stats["unschedulable_by_reason"] == {"capacity": 2}


# ---------------------------------------------------------------------------
# disarmed path
# ---------------------------------------------------------------------------


def test_disarmed_path_no_new_jit_outputs_after_armed_run():
    """Compile-cache counter check: arming explain compiles its OWN
    variant; re-running disarmed afterwards hits the original signature
    (zero new compilations) and returns the original 4-tuple."""
    # 17 clusters -> a padded cluster axis (C=32) no other test in this
    # module uses, so both the disarmed and armed signatures compile
    # fresh HERE and the cache arithmetic is unambiguous
    clusters = [mk_cluster(f"m-{i:02d}") for i in range(17)]
    placement = Placement(replica_scheduling=dyn_strategy())
    items = [(mk_spec(placement), ResourceBindingStatus())]
    cindex = tensors.ClusterIndex.build(clusters)
    disarmed = tensors.encode_batch(items, cindex)
    assert not disarmed.explain and not disarmed.pl_fail_bits.any()
    res = solve_compact(disarmed, waves=1)
    assert len(res) == 4
    c0 = _jit_cache_size()
    if c0 is None:
        pytest.skip("jit cache size not exposed on this jax")
    solve_compact(disarmed, waves=1)
    assert _jit_cache_size() == c0, "disarmed re-run must not recompile"
    armed = tensors.encode_batch(items, cindex, explain=True)
    res_a = solve_compact(armed, waves=1, explain=True)
    assert len(res_a) == 5 and len(res_a[-1]) == 4
    c1 = _jit_cache_size()
    assert c1 > c0, "explain must be its own jit variant"
    solve_compact(disarmed, waves=1)
    assert _jit_cache_size() == c1, (
        "disarmed dispatch after an armed run must reuse the original "
        "compiled program")


def test_disarmed_scheduler_records_zero_decisions():
    assert dec.recorder() is None
    cp = ControlPlane(backend="device", pipeline_chunk=2)
    cp.add_member("m1", cpu_milli=64_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version=GVK[0],
                                                 kind=GVK[1])],
            placement=Placement())))
    cp.apply({"apiVersion": GVK[0], "kind": GVK[1],
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 1, "template": {"spec": {"containers": [
                  {"name": "a", "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()
    assert cp.store.get("ResourceBinding", "default",
                        "app-deployment").spec.clusters
    assert dec.recorder() is None, "disarmed scheduler must not arm the ring"


# ---------------------------------------------------------------------------
# scheduler integration: queue reasons + metrics + spread/serial backends
# ---------------------------------------------------------------------------


def _plane(backend, explain=1.0, cpu="100m", replicas=2, members=2):
    cp = ControlPlane(backend=backend, pipeline_chunk=2, explain=explain)
    for i in range(members):
        cp.add_member(f"m{i + 1}", cpu_milli=1_000)
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version=GVK[0],
                                                 kind=GVK[1])],
            placement=Placement(replica_scheduling=dyn_strategy()))))
    cp.apply({"apiVersion": GVK[0], "kind": GVK[1],
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": replicas,
                       "template": {"spec": {"containers": [
                           {"name": "a", "resources": {
                               "requests": {"cpu": cpu}}}]}}}})
    cp.tick()
    return cp


def test_unschedulable_reason_reaches_queue_and_metric():
    from karmada_tpu.scheduler import metrics as sm

    before = sm.UNSCHEDULABLE.value(reason="capacity")
    cp = _plane("device", replicas=500, cpu="2000m")  # way over capacity
    reasons = cp.scheduler.queue.unschedulable_reasons()
    assert reasons.get("capacity", 0) >= 1, reasons
    assert sm.UNSCHEDULABLE.value(reason="capacity") > before
    d = dec.recorder().get("default/app-deployment")
    assert d is not None and d["outcome"] == "unschedulable"
    assert d["reason"] == "capacity"
    assert d in dec.recorder().unschedulable()


def test_serial_backend_records_decisions_too():
    cp = _plane("serial")
    d = dec.recorder().get("default/app-deployment")
    assert d is not None and d["backend"] == "serial"
    assert d["outcome"] == "scheduled" and d["targets"]


def test_region_spread_rows_record_full_verdict_decisions():
    """ROUTE_DEVICE_SPREAD bindings ride the spread sub-solve's explain
    callback: full per-cluster verdict tables, backend device-spread."""
    cp = ControlPlane(backend="device", pipeline_chunk=2, explain=1.0)
    cp.add_member("m1", cpu_milli=64_000, region="us")
    cp.add_member("m2", cpu_milli=64_000, region="eu")
    cp.tick()
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(api_version=GVK[0],
                                                 kind=GVK[1])],
            placement=Placement(
                spread_constraints=[SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_REGION,
                    min_groups=1, max_groups=2)],
                replica_scheduling=dyn_strategy()))))
    cp.apply({"apiVersion": GVK[0], "kind": GVK[1],
              "metadata": {"name": "app", "namespace": "default"},
              "spec": {"replicas": 4, "template": {"spec": {"containers": [
                  {"name": "a", "resources": {"requests": {"cpu": "100m"}}}]}}}})
    cp.tick()
    rb = cp.store.get("ResourceBinding", "default", "app-deployment")
    assert rb.spec.clusters
    d = dec.recorder().get("default/app-deployment")
    assert d is not None and d["backend"] == "device-spread"
    assert d["outcome"] == "scheduled"
    assert {c["name"] for c in d["clusters"]} == {"m1", "m2"}


# ---------------------------------------------------------------------------
# HTTP + karmadactl explain render smoke
# ---------------------------------------------------------------------------


def fetch(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


def test_explain_http_and_cli_smoke(capsys):
    from karmada_tpu import cli
    from karmada_tpu.utils.httpserve import ObservabilityServer

    cp = _plane("device")
    srv = ObservabilityServer(store=cp.store)
    base = srv.start()
    try:
        status, body = fetch(base + "/debug/explain")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        keys = {d["key"] for d in payload["decisions"]}
        assert "default/app-deployment" in keys
        status, body = fetch(base + "/debug/explain/default/app-deployment")
        assert status == 200
        one = json.loads(body)
        assert one["outcome"] == "scheduled"
        assert one["clusters"] and one["message"].startswith("scheduled to")
        # /debug/state folds the explain stats in
        state = json.loads(fetch(base + "/debug/state")[1])
        assert state["explain"]["recent"] >= 1

        # unknown binding: JSON 404 body (regression contract)
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch(base + "/debug/explain/default/nope")
        assert ei.value.code == 404
        assert "error" in json.loads(ei.value.read().decode())

        # karmadactl explain renders the one-liner + verdict table
        assert cli.main(["explain", "default/app-deployment",
                         "--endpoint", base]) == 0
        out = capsys.readouterr().out
        assert "BINDING: default/app-deployment" in out
        assert "CLUSTER" in out and "m1" in out
        # listing mode
        assert cli.main(["explain", "--endpoint", base]) == 0
        out = capsys.readouterr().out
        assert "default/app-deployment" in out
        # unknown binding -> clean error, exit 1
        assert cli.main(["explain", "default/nope",
                         "--endpoint", base]) == 1
    finally:
        srv.stop()


def test_explain_cli_reports_disarmed_plane(capsys):
    from karmada_tpu import cli
    from karmada_tpu.utils.httpserve import ObservabilityServer

    assert dec.recorder() is None
    srv = ObservabilityServer()
    base = srv.start()
    try:
        assert cli.main(["explain", "--endpoint", base]) == 1
        assert cli.main(["explain", "default/x", "--endpoint", base]) == 1
    finally:
        srv.stop()


def test_explain_kind_mode_still_works(capsys):
    from karmada_tpu import cli

    assert cli.main(["explain", "Cluster"]) == 0
    assert "KIND: Cluster" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# metric-naming vet pass (satellite)
# ---------------------------------------------------------------------------


def test_metric_naming_pass_flags_violations(tmp_path):
    from karmada_tpu.analysis.vet import run_vet

    bad = tmp_path / "mod.py"
    bad.write_text(
        "REGISTRY = object()\n"
        'A = REGISTRY.counter("bad_name_total", "has help")\n'
        'B = REGISTRY.gauge("karmada_no_help")\n'
        'C = REGISTRY.histogram("karmada_Bad_Case", "help")\n'
        'D = REGISTRY.counter(name, "dynamic name")\n'
        'E = REGISTRY.counter("karmada_fine_total", "all good")\n'
    )
    report = run_vet([str(bad)])
    msgs = [f.message for f in report.findings
            if f.rule == "metric-naming"]
    assert len(msgs) == 4, msgs
    assert any("bad_name_total" in m for m in msgs)
    assert any("karmada_no_help" in m for m in msgs)
    assert any("karmada_Bad_Case" in m for m in msgs)
    assert any("string literal" in m for m in msgs)
    # the live tree is clean under the new rule (tier-1 gate covers this
    # too; asserted here so a failure names the pass)
    import os

    pkg = os.path.join(os.path.dirname(__file__), "..", "karmada_tpu")
    live = run_vet([os.path.abspath(pkg)], rules=["metric-naming"])
    assert not live.findings, [f.message for f in live.findings]


def test_metric_naming_pass_sees_real_registrations():
    """The pass must actually be LOOKING at the package's registrations
    (an empty scan passing trivially would be a silent gate failure)."""
    import ast
    import os

    from karmada_tpu.analysis.core import collect_files
    from karmada_tpu.analysis.metric_naming import _registration

    pkg = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "karmada_tpu"))
    n = 0
    for sf in collect_files([pkg]):
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _registration(node):
                n += 1
    assert n >= 15, f"expected the pass to see many registrations, got {n}"
