"""Resource-modeling PRODUCER: grade histogram from node inventories.

Reference: pkg/modeling/modeling.go:33-246 (AddToResourceSummary/getIndex)
fed by the cluster-status controller (cluster_status_controller.go:282,
feature CustomizedClusterResourceModeling).  Round 2 only had the consumer
math (estimator/general.py); this covers the producing side.
"""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.estimator.general import produce_allocatable_modelings
from karmada_tpu.members.member import FakeMemberCluster, FakeNode
from karmada_tpu.models.cluster import (
    Cluster,
    ResourceModel,
    ResourceModelRange,
)
from karmada_tpu.utils.quantity import Quantity


def models():
    gi = 1024 ** 3
    return [
        ResourceModel(grade=0, ranges=[
            ResourceModelRange("cpu", Quantity.from_milli(0), Quantity.from_milli(2000)),
            ResourceModelRange("memory", Quantity.from_units(0), Quantity.from_units(8 * gi)),
        ]),
        ResourceModel(grade=1, ranges=[
            ResourceModelRange("cpu", Quantity.from_milli(2000), Quantity.from_milli(16000)),
            ResourceModelRange("memory", Quantity.from_units(8 * gi), Quantity.from_units(64 * gi)),
        ]),
        ResourceModel(grade=2, ranges=[
            ResourceModelRange("cpu", Quantity.from_milli(16000), Quantity.from_milli(1 << 40)),
            ResourceModelRange("memory", Quantity.from_units(64 * gi), Quantity.from_units(1 << 60)),
        ]),
    ]


def node(name, cpu_milli, mem_gi, pods=110):
    return FakeNode(name=name, cpu_milli=cpu_milli,
                    memory_milli=Quantity.parse(f"{mem_gi}Gi").milli, pods=pods)


def test_histogram_counts_nodes_by_grade():
    member = FakeMemberCluster(name="m1", nodes=[
        node("small", 1000, 4),     # grade 0
        node("medium", 8000, 32),   # grade 1
        node("medium2", 4000, 16),  # grade 1
        node("large", 32000, 128),  # grade 2
    ])
    got = {m.grade: m.count for m in produce_allocatable_modelings(member, models())}
    assert got == {0: 1, 1: 2, 2: 1}


def test_grade_is_minimum_across_axes():
    """A node with grade-2 cpu but grade-0 memory lands in grade 0
    (getIndex takes the min over the model's resource axes)."""
    member = FakeMemberCluster(name="m1", nodes=[node("skewed", 32000, 4)])
    got = {m.grade: m.count for m in produce_allocatable_modelings(member, models())}
    assert got == {0: 1, 1: 0, 2: 0}


def test_admitted_workloads_shrink_free_capacity():
    """The histogram models FREE capacity: admitted pods push a node down
    a grade, exactly what the estimator's consumer math then reads."""
    member = FakeMemberCluster(name="m1", nodes=[node("medium", 8000, 32)])
    assert {m.grade: m.count for m in produce_allocatable_modelings(member, models())} \
        == {0: 0, 1: 1, 2: 0}
    member.apply({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "hog", "namespace": "default"},
        "spec": {"replicas": 1, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "7", "memory": "28Gi"}}}]}}},
    })
    got = {m.grade: m.count for m in produce_allocatable_modelings(member, models())}
    assert got == {0: 1, 1: 0, 2: 0}


def test_cluster_status_controller_produces_modelings():
    cp = ControlPlane(backend="serial")
    m = cp.add_member("m1", cpu_milli=8000, memory_gi=32)

    def set_models(c: Cluster) -> None:
        c.spec.resource_models = models()
    cp.store.mutate(Cluster.KIND, "", "m1", set_models)
    cp.tick()
    cluster = cp.store.get(Cluster.KIND, "", "m1")
    histogram = {m.grade: m.count for m in
                 cluster.status.resource_summary.allocatable_modelings}
    assert histogram == {0: 0, 1: 1, 2: 0}
