"""--controllers= enable/disable list (controllermanager.go enablement
filtering): disabled controllers register but never run — neither their
reconcile workers nor their periodic hooks."""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.store.worker import parse_controllers


def deployment(name="web", replicas=2):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m"}}}]}}},
    }


def policy(cp, name="pp"):
    cp.apply_policy(PropagationPolicy(
        metadata=ObjectMeta(namespace="default", name=name),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment")],
            placement=Placement())))


def test_parse_controllers_semantics():
    star, on, off = parse_controllers("*")
    assert star and not on and not off
    star, on, off = parse_controllers("*,-descheduler,-mcs")
    assert star and off == {"descheduler", "mcs"}
    star, on, off = parse_controllers("detector,binding")
    assert not star and on == {"detector", "binding"}
    # default/empty means everything
    assert parse_controllers("")[0] and parse_controllers(None)[0]
    # unknown names are rejected up front (reference refuses to start)
    import pytest

    with pytest.raises(ValueError, match="taint-manger"):
        parse_controllers("*,-taint-manger")


def test_disabled_namespace_sync_does_not_propagate():
    cp = ControlPlane(controllers="*,-namespace-sync")
    cp.add_member("m1")
    cp.apply({"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "team-a", "namespace": ""}})
    cp.tick()
    assert cp.members["m1"].get("Namespace", "", "team-a") is None
    # the rest of the plane still works end to end
    policy(cp)
    cp.apply(deployment())
    cp.tick()
    assert cp.members["m1"].get("Deployment", "default", "web") is not None


def test_disabled_detector_stops_the_pipeline_at_the_source():
    from karmada_tpu.models.work import ResourceBinding

    cp = ControlPlane(controllers="*,-detector")
    cp.add_member("m1")
    policy(cp)
    cp.apply(deployment())
    cp.tick()
    assert not list(cp.store.list(ResourceBinding.KIND))


def test_allowlist_mode_runs_only_listed_controllers():
    from karmada_tpu.models.work import ResourceBinding, Work

    # detector creates bindings; scheduler schedules; but the binding
    # controller (not listed) never renders Work objects
    # the scheduler is a separate binary in the reference — never governed
    cp = ControlPlane(controllers="detector,deps-distributor")
    cp.add_member("m1")
    policy(cp)
    cp.apply(deployment())
    cp.tick()
    rbs = list(cp.store.list(ResourceBinding.KIND))
    assert len(rbs) == 1
    assert rbs[0].spec.clusters  # scheduled
    assert not list(cp.store.list(Work.KIND))  # binding controller off


def test_pull_agent_exempt_from_controller_filter():
    """Disabling 'execution' stops PUSH-side syncs but must not kill the
    pull-mode agent, which reuses the same controller classes (the agent
    is its own binary with its own flag in the reference)."""
    cp = ControlPlane(controllers="*,-execution,-work-status")
    cp.add_member("pull-m", sync_mode="Pull")
    cp.add_member("push-m")
    policy(cp)
    cp.apply(deployment())
    cp.tick()
    # the pull member's agent applied its Work; the push member got nothing
    assert cp.members["pull-m"].get("Deployment", "default", "web") is not None
    assert cp.members["push-m"].get("Deployment", "default", "web") is None


def test_detector_alias_covers_policy_worker():
    """'-detector' must disable BOTH detector workers (the policy queue is
    an internal alias, not a separately addressable controller)."""
    cp = ControlPlane(controllers="*,-detector")
    assert not cp.runtime.controller_enabled("detector")
    assert not cp.runtime.controller_enabled("detector-policy")
    disabled_names = {w.name for w in cp.runtime._disabled_workers}  # noqa: SLF001
    assert {"detector", "detector-policy"} <= disabled_names


def test_controllers_spec_persists_across_cli_invocations(tmp_path):
    from karmada_tpu.cli import main

    d = str(tmp_path / "plane")
    assert main(["--dir", d, "init"]) == 0
    assert main(["--dir", d, "join", "m1"]) == 0
    # tick with an explicit spec persists it
    assert main(["--dir", d, "tick", "--controllers",
                 "*,-namespace-sync"]) == 0
    cp = ControlPlane(persist_dir=d)  # rehydrates the persisted spec
    assert not cp.runtime.controller_enabled("namespace-sync")
    assert cp.runtime.controller_enabled("binding")
    # an invalid explicit spec is refused with a clean error
    assert main(["--dir", d, "tick", "--controllers", "*,-nope"]) == 1
