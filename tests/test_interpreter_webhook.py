"""Interpreter webhook tier: out-of-process customizations over HTTP.

Reference: pkg/resourceinterpreter/customized/webhook/ (engine) +
pkg/webhook/interpreter/ (host).  The webhook tier outranks every other
customization tier and its failures surface as errors, never as silent
fall-through.
"""

from __future__ import annotations

import pytest

from karmada_tpu.interpreter.interpreter import (
    OP_INTERPRET_HEALTH,
    OP_INTERPRET_REPLICA,
    OP_REVISE_REPLICA,
    ResourceInterpreter,
)
from karmada_tpu.interpreter.webhook import (
    InterpreterWebhookServer,
    WebhookCallError,
    unregister_local_endpoint,
)
from karmada_tpu.models.config import (
    InterpreterRule,
    ResourceInterpreterWebhook,
    ResourceInterpreterWebhookSpec,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.store.store import ObjectStore

GVK = {"apiVersion": "example.io/v1", "kind": "Widget"}


def widget(replicas=7):
    return {**GVK, "metadata": {"namespace": "default", "name": "w"},
            "spec": {"size": replicas}}


def make_server():
    srv = InterpreterWebhookServer()
    srv.handle("example.io/v1", "Widget", OP_INTERPRET_REPLICA,
               lambda req: {"replicas": req["object"]["spec"]["size"],
                            "requirements": {"cpu": "250m"}})
    srv.handle("example.io/v1", "Widget", OP_REVISE_REPLICA,
               lambda req: {"revised": {
                   **req["object"],
                   "spec": {**req["object"]["spec"],
                            "size": req["desiredReplicas"]},
               }})
    srv.handle("example.io/v1", "Widget", OP_INTERPRET_HEALTH,
               lambda req: {"healthy": req["object"]["spec"]["size"] < 100})
    return srv


def webhook_config(endpoint, name="widget-hook"):
    return ResourceInterpreterWebhook(
        metadata=ObjectMeta(name=name),
        spec=ResourceInterpreterWebhookSpec(
            endpoint=endpoint,
            rules=[InterpreterRule(api_versions=["example.io/v1"],
                                   kinds=["Widget"], operations=["*"])],
        ),
    )


def attach(interp, store, endpoint):
    store.create(webhook_config(endpoint))
    interp.attach_store(store)


def test_webhook_over_http_all_ops():
    srv = make_server()
    endpoint = srv.start()
    try:
        interp = ResourceInterpreter()
        attach(interp, ObjectStore(), endpoint)
        replicas, req = interp.get_replicas(widget(7))
        assert replicas == 7
        assert req is not None and req.resource_request["cpu"].milli == 250
        revised = interp.revise_replica(widget(7), 3)
        assert revised["spec"]["size"] == 3
        assert interp.interpret_health(widget(7)) == "Healthy"
        assert interp.interpret_health(widget(500)) == "Unhealthy"
    finally:
        srv.stop()


def test_webhook_local_endpoint_and_store_watch():
    srv = make_server()
    endpoint = srv.as_local_endpoint("widget-test")
    try:
        store = ObjectStore()
        interp = ResourceInterpreter()
        interp.attach_store(store)
        # config created AFTER attach: the watch subscription must pick it up
        store.create(webhook_config(endpoint))
        replicas, _ = interp.get_replicas(widget(11))
        assert replicas == 11
        # deleting the config removes the tier
        store.delete(ResourceInterpreterWebhook.KIND, "", "widget-hook")
        replicas, _ = interp.get_replicas(widget(11))
        assert replicas == 0  # native defaults know no Widget
    finally:
        unregister_local_endpoint("widget-test")


def test_webhook_failure_is_an_error_not_fallthrough():
    store = ObjectStore()
    interp = ResourceInterpreter()
    interp.attach_store(store)
    store.create(webhook_config("local:definitely-absent"))
    with pytest.raises(WebhookCallError):
        interp.get_replicas(widget(1))


def test_webhook_outranks_declarative_tier():
    from karmada_tpu.models.config import (
        CustomizationTarget,
        ResourceInterpreterCustomization,
        ResourceInterpreterCustomizationSpec,
    )

    srv = make_server()
    endpoint = srv.as_local_endpoint("widget-priority")
    try:
        store = ObjectStore()
        store.create(ResourceInterpreterCustomization(
            metadata=ObjectMeta(name="declarative-widget"),
            spec=ResourceInterpreterCustomizationSpec(
                target=CustomizationTarget(api_version="example.io/v1",
                                           kind="Widget"),
                customizations={OP_INTERPRET_REPLICA: "999"},
            ),
        ))
        store.create(webhook_config(endpoint))
        interp = ResourceInterpreter()
        interp.attach_store(store)
        replicas, _ = interp.get_replicas(widget(7))
        assert replicas == 7  # webhook answer, not the declarative 999
    finally:
        unregister_local_endpoint("widget-priority")


def test_empty_rule_matches_nothing():
    store = ObjectStore()
    interp = ResourceInterpreter()
    interp.attach_store(store)
    cfg = webhook_config("local:absent", name="empty-rule")
    cfg.spec.rules = [InterpreterRule()]  # all pattern lists empty
    store.create(cfg)
    # native Deployment interpretation must be untouched
    replicas, _ = interp.get_replicas({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"namespace": "d", "name": "x"},
        "spec": {"replicas": 5, "template": {"spec": {"containers": []}}},
    })
    assert replicas == 5


def test_local_handler_fault_is_webhook_call_error():
    srv = InterpreterWebhookServer()
    srv.handle("example.io/v1", "Widget", OP_INTERPRET_REPLICA,
               lambda req: None)  # buggy handler: no response dict
    endpoint = srv.as_local_endpoint("widget-buggy")
    try:
        store = ObjectStore()
        interp = ResourceInterpreter()
        interp.attach_store(store)
        store.create(webhook_config(endpoint))
        with pytest.raises(WebhookCallError):
            interp.get_replicas(widget(1))
    finally:
        unregister_local_endpoint("widget-buggy")


def test_aggregate_status_returns_full_manifest():
    from karmada_tpu.interpreter.interpreter import OP_AGGREGATE_STATUS
    from karmada_tpu.models.work import AggregatedStatusItem

    srv = InterpreterWebhookServer()
    srv.handle("example.io/v1", "Widget", OP_AGGREGATE_STATUS,
               lambda req: {"status": {"readyTotal": sum(
                   i["status"].get("ready", 0)
                   for i in req["aggregatedStatusItems"])}})
    endpoint = srv.as_local_endpoint("widget-agg")
    try:
        store = ObjectStore()
        interp = ResourceInterpreter()
        interp.attach_store(store)
        store.create(webhook_config(endpoint))
        items = [AggregatedStatusItem(cluster_name="m1", status={"ready": 2}),
                 AggregatedStatusItem(cluster_name="m2", status={"ready": 3})]
        merged = interp.aggregate_status(widget(7), items)
        assert merged["kind"] == "Widget"  # full manifest, not a bare status
        assert merged["status"] == {"readyTotal": 5}
    finally:
        unregister_local_endpoint("widget-agg")
