"""Golden tests transcribed from reference pkg/scheduler/core/assignment_test.go.

These pin the serial control path to the reference's exact semantics; the TPU
solver is then property-tested against the serial path.
"""

import pytest

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    StaticClusterWeight,
)
from karmada_tpu.models.work import (
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial
from karmada_tpu.ops.serial import ClusterDetailInfo, UnschedulableError, assign_replicas


def detail(name: str, allocatable: int = 0) -> ClusterDetailInfo:
    return ClusterDetailInfo(
        name=name,
        score=0,
        available_replicas=allocatable,
        allocatable_replicas=allocatable,
        cluster=Cluster(metadata=ObjectMeta(name=name)),
    )


def static_strategy(weights=None):
    wp = None
    if weights is not None:
        wp = ClusterPreferences(
            static_weight_list=[
                StaticClusterWeight(
                    target_cluster=ClusterAffinity(cluster_names=[n]), weight=w
                )
                for n, w in weights
            ]
        )
    return ReplicaSchedulingStrategy(
        replica_scheduling_type="Divided",
        replica_division_preference="Weighted",
        weight_preference=wp,
    )


DYNAMIC = ReplicaSchedulingStrategy(
    replica_scheduling_type="Divided",
    replica_division_preference="Weighted",
    weight_preference=ClusterPreferences(dynamic_weight="AvailableReplicas"),
)
AGGREGATED = ReplicaSchedulingStrategy(
    replica_scheduling_type="Divided",
    replica_division_preference="Aggregated",
)


def spec_for(strategy, replicas, clusters=(), requirements=True):
    return ResourceBindingSpec(
        replicas=replicas,
        replica_requirements=ReplicaRequirements() if requirements else None,
        clusters=[TargetCluster(name=n, replicas=r) for n, r in clusters],
        placement=Placement(replica_scheduling=strategy),
    )


def as_map(result):
    return {tc.name: tc.replicas for tc in result}


# --- Test_assignByStaticWeightStrategy --------------------------------------


@pytest.mark.parametrize(
    "replicas,weights,want",
    [
        (12, [("m1", 3), ("m2", 2), ("m3", 1)], {"m1": 6, "m2": 4, "m3": 2}),
        (12, None, {"m1": 4, "m2": 4, "m3": 4}),
        (13, [("m1", 3), ("m2", 2), ("m3", 1)], {"m1": 7, "m2": 4, "m3": 2}),
        (14, [("m1", 3), ("m2", 2), ("m3", 1)], {"m1": 7, "m2": 5, "m3": 2}),
    ],
)
def test_static_weight(replicas, weights, want):
    candidates = [detail("m1"), detail("m2"), detail("m3")]
    spec = spec_for(static_strategy(weights), replicas)
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == want


def test_static_weight_cluster_without_weight_ignored():
    candidates = [detail("m1"), detail("m2")]
    spec = spec_for(static_strategy([("m1", 1)]), 2)
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 2}


def test_static_weight_multiple_weights_takes_max():
    candidates = [detail("m1"), detail("m2")]
    spec = spec_for(static_strategy([("m1", 1), ("m2", 1), ("m1", 2)]), 3)
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 2, "m2": 1}


def test_static_weight_zero_replicas():
    candidates = [detail("m1"), detail("m2")]
    spec = spec_for(static_strategy([("m1", 1), ("m2", 1)]), 0)
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {}  # zero-replica clusters stripped


# --- Test_dynamicScale ------------------------------------------------------


def test_dynamic_weighted_scale_down_12_to_6():
    candidates = [detail("m1", 1), detail("m2", 1), detail("m3", 1)]
    spec = spec_for(DYNAMIC, 6, [("m1", 2), ("m2", 4), ("m3", 6)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 1, "m2": 2, "m3": 3}


def test_dynamic_weighted_scale_up_12_to_24():
    candidates = [detail("m1", 10), detail("m2", 10), detail("m3", 10)]
    spec = spec_for(DYNAMIC, 24, [("m1", 2), ("m2", 4), ("m3", 6)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 6, "m2": 8, "m3": 10}


def test_dynamic_weighted_scale_up_insufficient():
    candidates = [detail("m1", 1), detail("m2", 1), detail("m3", 1)]
    spec = spec_for(DYNAMIC, 24, [("m1", 2), ("m2", 4), ("m3", 6)])
    with pytest.raises(UnschedulableError):
        assign_replicas(candidates, spec, ResourceBindingStatus())


def test_aggregated_scale_down_12_to_6():
    candidates = [detail("m1", 1), detail("m2", 1), detail("m3", 1)]
    spec = spec_for(AGGREGATED, 6, [("m1", 4), ("m2", 8)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m2": 6}


def test_aggregated_scale_down_12_to_8():
    candidates = [detail("m1", 100), detail("m2", 100)]
    spec = spec_for(AGGREGATED, 8, [("m1", 4), ("m2", 8)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m2": 8}


def test_aggregated_scale_up_4_6_8():
    candidates = [detail("m1", 4), detail("m2", 6), detail("m3", 8)]
    spec = spec_for(AGGREGATED, 24, [("m1", 4), ("m2", 8)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 7, "m2": 12, "m3": 5}


def test_aggregated_scale_up_6_6_20():
    candidates = [detail("m1", 6), detail("m2", 6), detail("m3", 20)]
    spec = spec_for(AGGREGATED, 24, [("m1", 4), ("m2", 8)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 10, "m2": 14}


def test_aggregated_scale_up_insufficient():
    candidates = [detail("m1", 1), detail("m2", 1), detail("m3", 1)]
    spec = spec_for(AGGREGATED, 24, [("m1", 4), ("m2", 8)])
    with pytest.raises(UnschedulableError):
        assign_replicas(candidates, spec, ResourceBindingStatus())


def test_aggregated_cluster_disappeared_and_appeared():
    candidates = [detail("m1", 4), detail("m3", 8), detail("m4", 12)]
    spec = spec_for(AGGREGATED, 24, [("m1", 4), ("m2", 8)])
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 7, "m3": 7, "m4": 10}


def test_duplicated_strategy():
    candidates = [detail("m1"), detail("m2")]
    spec = ResourceBindingSpec(
        replicas=5,
        replica_requirements=ReplicaRequirements(),
        placement=Placement(
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type="Duplicated"
            )
        ),
    )
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 5, "m2": 5}


def test_non_workload_propagates_to_all():
    candidates = [detail("m1"), detail("m2")]
    spec = ResourceBindingSpec(replicas=0, replica_requirements=None,
                               placement=Placement())
    got = assign_replicas(candidates, spec, ResourceBindingStatus())
    assert as_map(got) == {"m1": 0, "m2": 0}


def test_no_candidates_raises():
    spec = spec_for(DYNAMIC, 3)
    with pytest.raises(serial.NoClusterAvailableError):
        assign_replicas([], spec, ResourceBindingStatus())
