from karmada_tpu.utils.quantity import Quantity, parse_quantity, resource_request_value


def test_parse_plain():
    assert parse_quantity("2").milli == 2000
    assert parse_quantity(3).milli == 3000
    assert parse_quantity("0").milli == 0


def test_parse_milli():
    assert parse_quantity("100m").milli == 100
    assert parse_quantity("1500m").value() == 2  # Value() rounds up
    assert parse_quantity("1500m").milli_value() == 1500


def test_parse_binary_suffixes():
    assert parse_quantity("1Ki").value() == 1024
    assert parse_quantity("2Gi").value() == 2 * 2**30
    assert parse_quantity("1Mi").milli == 1000 * 2**20


def test_parse_decimal_suffixes():
    assert parse_quantity("1k").value() == 1000
    assert parse_quantity("2M").value() == 2_000_000
    assert parse_quantity("1.5G").value() == 1_500_000_000


def test_parse_fraction():
    assert parse_quantity("0.5").milli == 500
    assert parse_quantity("1.5Gi").value() == 3 * 2**29


def test_parse_exponent():
    assert parse_quantity("1e3").value() == 1000
    assert parse_quantity("1.2e2").milli == 120_000


def test_arithmetic():
    a, b = parse_quantity("2"), parse_quantity("500m")
    assert (a - b).milli == 1500
    assert (a + b).value() == 3  # 2.5 rounds up


def test_resource_request_value_cpu_vs_other():
    q = parse_quantity("1500m")
    assert resource_request_value("cpu", q) == 1500
    assert resource_request_value("memory", q) == 2


def test_quantity_order():
    assert parse_quantity("100m") < parse_quantity("1")
    assert Quantity(0).is_zero()
