"""Golden parity: batched TPU pipeline vs the serial control path.

Randomized scenarios within the device-supported class (no topology-spread
DFS, single component) must produce identical schedule results -- same
target clusters, same replica counts, same error classes -- as
ops/serial.schedule, binding by binding.
"""

import random

import pytest

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    AllocatableModeling,
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceModel,
    ResourceModelRange,
    ResourceSummary,
    Taint,
)
from karmada_tpu.models.meta import LabelSelector, ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
    StaticClusterWeight,
    Toleration,
)
from karmada_tpu.models.work import (
    GracefulEvictionTask,
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial
from karmada_tpu.ops import tensors
from karmada_tpu.ops.solver import solve
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


def mk_cluster(rng, name):
    labels = {}
    if rng.random() < 0.5:
        labels["tier"] = rng.choice(["gold", "silver"])
    taints = []
    if rng.random() < 0.3:
        taints.append(Taint(key="dedicated", value="infra", effect="NoSchedule"))
    summary = None
    models = []
    if rng.random() < 0.9:
        summary = ResourceSummary(
            allocatable={
                "cpu": Quantity.from_milli(rng.randint(0, 64000)),
                "memory": Quantity.from_units(rng.randint(0, 256)),
                "pods": Quantity.from_units(rng.randint(0, 200)),
            },
            allocated={
                "cpu": Quantity.from_milli(rng.randint(0, 16000)),
                "memory": Quantity.from_units(rng.randint(0, 64)),
                "pods": Quantity.from_units(rng.randint(0, 50)),
            },
        )
        if rng.random() < 0.2:
            # histogram-modeled cluster: exercises the host override path
            models = [
                ResourceModel(grade=0, ranges=[
                    ResourceModelRange("cpu", Quantity.from_milli(0), Quantity.from_milli(2000)),
                    ResourceModelRange("memory", Quantity.from_units(0), Quantity.from_units(8)),
                ]),
                ResourceModel(grade=1, ranges=[
                    ResourceModelRange("cpu", Quantity.from_milli(2000), Quantity.from_milli(64000)),
                    ResourceModelRange("memory", Quantity.from_units(8), Quantity.from_units(256)),
                ]),
            ]
            summary.allocatable_modelings = [
                AllocatableModeling(grade=0, count=rng.randint(0, 5)),
                AllocatableModeling(grade=1, count=rng.randint(0, 5)),
            ]
    enablements = [APIEnablement(GVK[0], [GVK[1]])] if rng.random() < 0.9 else []
    meta = ObjectMeta(name=name, labels=labels)
    if rng.random() < 0.05:
        meta.deletion_timestamp = 1.0
    return Cluster(
        metadata=meta,
        spec=ClusterSpec(
            region=rng.choice(["us", "eu"]),
            provider=rng.choice(["aws", ""]),
            taints=taints,
            resource_models=models,
        ),
        status=ClusterStatus(api_enablements=enablements, resource_summary=summary),
    )


def mk_placement(rng, names):
    affinity = None
    r = rng.random()
    if r < 0.3:
        affinity = ClusterAffinity(cluster_names=rng.sample(names, rng.randint(1, len(names))))
    elif r < 0.5:
        affinity = ClusterAffinity(label_selector=LabelSelector(match_labels={"tier": "gold"}))
    tolerations = []
    if rng.random() < 0.5:
        tolerations.append(Toleration(key="dedicated", operator="Exists"))
    spread = []
    if rng.random() < 0.4:
        mn = rng.randint(1, 3)
        spread.append(SpreadConstraint(
            spread_by_field=SPREAD_BY_FIELD_CLUSTER,
            min_groups=mn, max_groups=rng.randint(mn, 5),
        ))
        if rng.random() < 0.3:
            # provider/zone alongside cluster: filters only (clusters
            # without the property drop out); selection stays by-cluster
            # and the binding stays on device
            from karmada_tpu.models.policy import (
                SPREAD_BY_FIELD_PROVIDER,
                SPREAD_BY_FIELD_ZONE,
            )

            spread.append(SpreadConstraint(
                spread_by_field=rng.choice([SPREAD_BY_FIELD_PROVIDER,
                                            SPREAD_BY_FIELD_ZONE]),
                min_groups=1, max_groups=rng.randint(1, 3),
            ))
    strat = rng.choice(["dup", "static", "dynamic", "agg"])
    if strat == "dup":
        rs = ReplicaSchedulingStrategy(replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    elif strat == "static":
        wl = []
        if rng.random() < 0.7:
            for nm in rng.sample(names, rng.randint(1, len(names))):
                wl.append(StaticClusterWeight(
                    target_cluster=ClusterAffinity(cluster_names=[nm]),
                    weight=rng.randint(0, 3),
                ))
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(static_weight_list=wl) if wl else None,
        )
    elif strat == "dynamic":
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        )
    else:
        rs = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED,
        )
    return Placement(
        cluster_affinity=affinity,
        cluster_tolerations=tolerations,
        spread_constraints=spread,
        replica_scheduling=rs,
    )


def mk_binding(rng, b, names, placements):
    reqs = None
    if rng.random() < 0.7:
        reqs = ReplicaRequirements(resource_request={
            "cpu": Quantity.from_milli(rng.choice([100, 250, 500, 1000])),
            "memory": Quantity.from_units(rng.choice([1, 2, 4])),
        })
    spec = ResourceBindingSpec(
        resource=ObjectReference(
            api_version=GVK[0], kind=GVK[1], namespace="default",
            name=f"app-{b}", uid=f"uid-{rng.randint(0, 10**9)}",
        ),
        replicas=rng.choice([0, 1, 3, 10, 40]),
        replica_requirements=reqs,
        placement=rng.choice(placements),
    )
    status = ResourceBindingStatus()
    if rng.random() < 0.4:  # previous assignment (steady-mode paths)
        prev = rng.sample(names, rng.randint(1, min(3, len(names))))
        spec.clusters = [TargetCluster(name=n, replicas=rng.randint(0, 20)) for n in prev]
        status.last_scheduled_time = 100.0
        if rng.random() < 0.3:  # reschedule trigger -> Fresh mode
            spec.reschedule_triggered_at = 200.0
    if rng.random() < 0.15:
        spec.graceful_eviction_tasks = [
            GracefulEvictionTask(from_cluster=rng.choice(names))
        ]
    return spec, status


def run_parity(seed, n_clusters=11, n_bindings=24):
    # 11 clusters pad to C=16: padded lanes flow through selection/division
    rng = random.Random(seed)
    names = [f"member-{i:02d}" for i in range(n_clusters)]
    clusters = [mk_cluster(rng, nm) for nm in names]
    placements = [mk_placement(rng, names) for _ in range(5)]
    items = [mk_binding(rng, b, names, placements) for b in range(n_bindings)]

    estimator = GeneralEstimator()
    cal = serial.make_cal_available([estimator])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, estimator)
    assert (batch.route == tensors.ROUTE_DEVICE).all(), "scenario must stay on-device"
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status, items=items)

    for b, (spec, st) in enumerate(items):
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001
            assert isinstance(got[b], type(e)), (
                f"seed={seed} b={b}: serial raised {type(e).__name__}, "
                f"device gave {got[b]!r}"
            )
            if isinstance(e, serial.FitError):
                # device path must carry the same per-cluster diagnosis
                assert got[b].diagnosis == e.diagnosis, (
                    f"seed={seed} b={b}: diagnosis mismatch"
                )
            continue
        assert not isinstance(got[b], Exception), (
            f"seed={seed} b={b}: serial={want}, device error {got[b]!r}"
        )
        want_map = {tc.name: tc.replicas for tc in want}
        got_map = {tc.name: tc.replicas for tc in got[b]}
        assert got_map == want_map, (
            f"seed={seed} b={b} strat={serial.strategy_type(spec)}: "
            f"serial={want_map} device={got_map}"
        )


@pytest.mark.parametrize("seed", range(12))
def test_batch_parity_random(seed):
    run_parity(seed)


def test_capacity_matches_general_estimator():
    rng = random.Random(7)
    names = [f"m{i}" for i in range(12)]
    clusters = [mk_cluster(rng, nm) for nm in names]
    reqs = ReplicaRequirements(resource_request={
        "cpu": Quantity.from_milli(300), "memory": Quantity.from_units(2),
    })
    spec = ResourceBindingSpec(
        resource=ObjectReference(api_version=GVK[0], kind=GVK[1], name="x", uid="u"),
        replicas=5, replica_requirements=reqs,
        placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        )),
    )
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch([(spec, ResourceBindingStatus())], cindex, est)
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status)[0]
    want = serial.schedule(spec, ResourceBindingStatus(), clusters, cal)
    assert {t.name: t.replicas for t in got} == {t.name: t.replicas for t in want}


def test_topology_spread_routing():
    rng = random.Random(3)
    names = ["a", "b"]
    clusters = [mk_cluster(rng, nm) for nm in names]

    def spec_with(field):
        return ResourceBindingSpec(
            resource=ObjectReference(api_version=GVK[0], kind=GVK[1], name="x", uid="u"),
            replicas=4,
            placement=Placement(spread_constraints=[
                SpreadConstraint(spread_by_field=field, min_groups=1, max_groups=2),
                SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER, min_groups=1, max_groups=2),
            ]),
        )

    cindex = tensors.ClusterIndex.build(clusters)
    # region spread with few regions: the device spread path (ops/spread.py)
    batch = tensors.encode_batch([(spec_with("region"), ResourceBindingStatus())], cindex)
    assert batch.route[0] == tensors.ROUTE_DEVICE_SPREAD
    # provider/zone ALONGSIDE a cluster constraint: feasibility filter
    # only — stays on device (selection is by-cluster)
    for field in ("provider", "zone"):
        batch = tensors.encode_batch([(spec_with(field), ResourceBindingStatus())], cindex)
        assert batch.route[0] == tensors.ROUTE_DEVICE


def test_jit_signature_stable_across_vocab_churn():
    """Q/P/G/R vocabulary churn must not change the jitted shapes.

    A live control plane sees a different number of distinct placements /
    request classes / GVKs every cycle; each axis is pow2-bucketed so the
    compile cache holds (VERDICT r1 weak #4)."""
    rng = random.Random(21)
    names = [f"m{i}" for i in range(11)]
    clusters = [mk_cluster(rng, nm) for nm in names]
    cindex = tensors.ClusterIndex.build(clusters)

    def shapes(items):
        batch = tensors.encode_batch(items, cindex)
        return {
            f: getattr(batch, f).shape
            for f in ("req_milli", "req_is_cpu", "est_override", "pl_mask",
                      "pl_strategy", "api_ok")
        }

    # 1 placement, 1 class, 1 gvk, 2 resources
    one = [mk_binding(rng, 0, names, [mk_placement(rng, names)])]
    # 3 placements, several classes, 2 gvks (all under the bucket minima);
    # request classes pinned to 3 distinct profiles so the Q axis stays
    # under its bucket regardless of generator drift
    placements = [mk_placement(rng, names) for _ in range(3)]
    many = [mk_binding(rng, b, names, placements) for b in range(8)]
    profiles = [
        {"cpu": Quantity.from_milli(100), "memory": Quantity.from_units(1)},
        {"cpu": Quantity.from_milli(250), "memory": Quantity.from_units(2)},
        {"cpu": Quantity.from_milli(500)},
    ]
    for b, (spec, _st) in enumerate(many):
        if spec.replica_requirements is not None:
            spec.replica_requirements.resource_request = dict(
                profiles[b % 3])
    many[0][0].resource.kind = "StatefulSet"

    assert shapes(one) == shapes(many)

    # crossing a bucket boundary rounds up to the next pow2, not exact size
    nine = [ClusterAffinity(cluster_names=[nm]) for nm in names[:9]]
    over = [
        mk_binding(rng, b, names, [Placement(
            cluster_affinity=aff,
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED),
        )])
        for b, aff in enumerate(nine)
    ]
    batch = tensors.encode_batch(over, tensors.ClusterIndex.build(clusters))
    assert batch.pl_mask.shape[0] == 16  # 9 placements -> pow2 bucket


def test_device_fit_error_carries_serial_diagnosis():
    """FIT_ERROR decode rebuilds the per-cluster diagnosis (operator's main
    debugging signal) identical to the serial path's FitError."""
    rng = random.Random(5)
    clusters = [mk_cluster(rng, f"m{i}") for i in range(6)]
    # make every cluster infeasible: affinity names nobody has
    spec = ResourceBindingSpec(
        resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                 name="x", uid="u"),
        replicas=3,
        placement=Placement(
            cluster_affinity=ClusterAffinity(cluster_names=["absent-1", "absent-2"]),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED),
        ),
    )
    items = [(spec, ResourceBindingStatus())]
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex)
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status, items=items)[0]
    assert isinstance(got, serial.FitError)
    try:
        serial.schedule(spec, ResourceBindingStatus(), clusters,
                        serial.make_cal_available([GeneralEstimator()]))
        raise AssertionError("serial must also FitError")
    except serial.FitError as e:
        assert got.diagnosis == e.diagnosis
        assert len(got.diagnosis) == len(clusters)


def test_compact_extraction_excludes_plain_selection_lanes():
    """A full-fleet Divided binding's selection is its whole feasible set;
    the COO extraction must NOT ship those zero-replica lanes (regression:
    they degenerated the compact result to dense size at 100k x 5k, a
    ~270 MB D2H per chunk).  keep_sel (empty-workload propagation) and
    non-workload bindings still get their selected lanes."""
    import random

    import bench
    from karmada_tpu.ops import tensors
    from karmada_tpu.ops.solver import solve_compact
    from karmada_tpu.estimator.general import GeneralEstimator

    rng = random.Random(5)
    clusters = bench.build_fleet(rng, 256)
    # dynamic-weight over the whole fleet: feasible/selected on all lanes
    placements = [p for p in bench.build_placements(rng, [c.name for c in clusters])
                  if p.replica_scheduling is not None
                  and p.cluster_affinity is None
                  and not p.spread_constraints][:2]
    assert placements
    items = bench.build_bindings(rng, 32, placements)
    batch = tensors.encode_batch(items, tensors.ClusterIndex.build(clusters),
                                 GeneralEstimator())
    idx, val, status, nnz = solve_compact(batch, waves=2)
    # without keep_sel: only actual assignments ship (< a few per binding)
    assert int(nnz) <= 32 * 16, int(nnz)
    assert (val[idx >= 0] > 0).all()
    # with keep_sel: the selection lanes (whole fleet) are included
    _, val_k, _, nnz_k = solve_compact(batch, waves=2, keep_sel=True)
    assert int(nnz_k) > 32 * 64, int(nnz_k)


@pytest.mark.parametrize("seed", range(6))
def test_batch_parity_random_compact_lanes(seed):
    """C=600 > COMPACT_LANES: the kernel's top-K gather path must stay
    bit-identical to serial — including Webster tie blocks, the static
    all-equal-weight fallback, aggregated prefixes, selection swaps, and
    uid-flipped tiebreak order, all of which constrain WHICH lanes the
    gather must contain."""
    run_parity(seed, n_clusters=600, n_bindings=16)


def test_provider_zone_spread_routing():
    """Provider/zone constraints: alongside cluster/region selection they
    stay on device (pure feasibility filters); alone they go host for the
    reference's 'just support cluster and region' UnschedulableError
    (select_clusters.go:55)."""
    from karmada_tpu.models.policy import (
        SPREAD_BY_FIELD_PROVIDER,
        SPREAD_BY_FIELD_ZONE,
    )

    rng = random.Random(1)
    names = [f"member-{i:02d}" for i in range(8)]
    clusters = [mk_cluster(rng, nm) for nm in names]
    for c in clusters:  # deterministic usable fleet for this check
        c.metadata.deletion_timestamp = None
        c.spec.provider = "aws"
        c.status.api_enablements = [APIEnablement(GVK[0], [GVK[1]])]

    def binding(scs):
        spec = ResourceBindingSpec(
            resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                     namespace="ns", name="a", uid="u"),
            replicas=4,
            replica_requirements=ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(100)}),
            placement=Placement(
                spread_constraints=scs,
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                    replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                    weight_preference=ClusterPreferences(
                        dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)),
            ),
        )
        return spec, ResourceBindingStatus()

    provider_sc = SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_PROVIDER,
                                   min_groups=1, max_groups=2)
    zone_sc = SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_ZONE,
                               min_groups=1, max_groups=2)
    cluster_sc = SpreadConstraint(spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                                  min_groups=1, max_groups=3)
    items = [
        binding([provider_sc, cluster_sc]),   # on device
        binding([provider_sc]),               # host: UnschedulableError
        binding([zone_sc]),                   # host (zone filter empties
                                              # the fleet first: FitError)
    ]
    cindex = tensors.ClusterIndex.build(clusters)
    est = GeneralEstimator()
    batch = tensors.encode_batch(items, cindex, est)
    assert batch.route[0] == tensors.ROUTE_DEVICE
    assert batch.route[1] == tensors.ROUTE_TOPOLOGY_SPREAD
    assert batch.route[2] == tensors.ROUTE_TOPOLOGY_SPREAD

    cal = serial.make_cal_available([est])
    rep, sel, status = solve(batch)
    got = tensors.decode_result(batch, rep, sel, status, items=items)
    want = serial.schedule(items[0][0], items[0][1], clusters, cal)
    assert ({tc.name: tc.replicas for tc in got[0]}
            == {tc.name: tc.replicas for tc in want})
    with pytest.raises(serial.UnschedulableError):
        serial.schedule(items[1][0], items[1][1], clusters, cal)
    with pytest.raises(serial.FitError):
        serial.schedule(items[2][0], items[2][1], clusters, cal)


def test_batch_parity_wide_cluster_axis():
    """C=16,384 — above the r3 13-bit lane cap (8192): the widened 21-bit
    key packing (solver._LANE_BITS) must keep the compact-lane path
    bit-identical to serial at fleet sizes the old packing rejected."""
    from karmada_tpu.ops import solver

    assert solver.MAX_CLUSTER_LANES >= 16384
    run_parity(3, n_clusters=16384 - 5, n_bindings=6)


def test_compact_cap_routing():
    """Bindings beyond the tier-1 compact caps route to the big-tier
    device sub-solve at large C; beyond the big caps they route host;
    at small C everything stays on the direct device path."""
    rng = random.Random(3)
    names = [f"member-{i:03d}" for i in range(600)]
    clusters = [mk_cluster(rng, nm) for nm in names]
    placement = Placement(replica_scheduling=ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
        replica_division_preference=REPLICA_DIVISION_WEIGHTED,
        weight_preference=ClusterPreferences(
            dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
    ))

    def binding(replicas, prev_n=0, dup=False, sc_max=0):
        pl = placement
        if dup:
            pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED))
        if sc_max:
            pl = Placement(
                spread_constraints=[SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                    min_groups=1, max_groups=sc_max)],
                replica_scheduling=pl.replica_scheduling,
            )
        spec = ResourceBindingSpec(
            resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                     namespace="d", name="x", uid="u"),
            replicas=replicas, placement=pl,
        )
        if prev_n:
            spec.clusters = [TargetCluster(name=n, replicas=1)
                             for n in names[:prev_n]]
        return spec, ResourceBindingStatus()

    items = [
        binding(50),            # divided, under cap -> device
        binding(100),           # divided, over the 64-replica cap -> BIG tier
        binding(100, dup=True),  # duplicated: replica cap does not apply
        binding(10, prev_n=20),  # 20 prev clusters > 16 cap -> BIG tier
        binding(10, sc_max=80),  # selection 64 < 80 <= 512 -> BIG tier
        binding(600),            # beyond the big division cap -> host
        binding(10, prev_n=140),  # beyond the big prev cap -> host
        binding(10, sc_max=600),  # beyond the big selection cap -> host
    ]
    batch = tensors.encode_batch(
        items, tensors.ClusterIndex.build(clusters), GeneralEstimator())
    assert batch.route[0] == tensors.ROUTE_DEVICE
    assert batch.route[1] == tensors.ROUTE_DEVICE_BIG
    assert batch.route[2] == tensors.ROUTE_DEVICE
    assert batch.route[3] == tensors.ROUTE_DEVICE_BIG
    assert batch.route[4] == tensors.ROUTE_DEVICE_BIG
    assert batch.route[5] == tensors.ROUTE_COMPACT_CAP
    assert batch.route[6] == tensors.ROUTE_COMPACT_CAP
    assert batch.route[7] == tensors.ROUTE_COMPACT_CAP

    # the same bindings at small C all stay on-device (no gather, no caps)
    small = clusters[:16]
    batch_small = tensors.encode_batch(
        [binding(100), binding(10, prev_n=10), binding(10, sc_max=80)],
        tensors.ClusterIndex.build(small), GeneralEstimator())
    assert (batch_small.route == tensors.ROUTE_DEVICE).all()


@pytest.mark.parametrize("seed", range(4))
def test_big_tier_parity(seed):
    """ROUTE_DEVICE_BIG (replicas/prev/MaxGroups beyond the tier-1 caps):
    the big-lane sub-solve must stay bit-identical to serial."""
    from karmada_tpu.ops.solver import solve_big

    rng = random.Random(seed)
    names = [f"member-{i:03d}" for i in range(700)]
    clusters = [mk_cluster(rng, nm) for nm in names]

    def big_binding(b):
        style = b % 3
        if style == 0:  # big replica count
            pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
            spec = ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"a{b}",
                                         uid=f"u{b}"),
                replicas=rng.randint(65, 400), placement=pl)
        elif style == 1:  # wide selection
            pl = Placement(
                spread_constraints=[SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                    min_groups=2, max_groups=rng.randint(65, 300))],
                replica_scheduling=ReplicaSchedulingStrategy(
                    replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                    replica_division_preference=REPLICA_DIVISION_AGGREGATED))
            spec = ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"a{b}",
                                         uid=f"u{b}"),
                replicas=rng.randint(5, 60), placement=pl)
        else:  # many previous clusters (steady scale paths)
            pl = Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)))
            prev_n = rng.randint(17, 100)
            spec = ResourceBindingSpec(
                resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                         namespace="d", name=f"a{b}",
                                         uid=f"u{b}"),
                replicas=rng.randint(30, 120), placement=pl,
                clusters=[TargetCluster(name=n, replicas=1)
                          for n in rng.sample(names, prev_n)])
        if rng.random() < 0.4:
            spec.replica_requirements = ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(rng.choice([100, 250]))})
        return spec, ResourceBindingStatus()

    items = [big_binding(b) for b in range(6)]
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, est)
    big_idx = [i for i in range(len(items))
               if batch.route[i] == tensors.ROUTE_DEVICE_BIG]
    assert big_idx, "scenario must exercise the big tier"
    # waves=1: the serial comparison is per-binding against the untouched
    # snapshot (contention parity is covered by test_contention)
    got = solve_big(items, big_idx, cindex, est, None, waves=1)
    for i in big_idx:
        spec, st = items[i]
        try:
            want = {tc.name: tc.replicas
                    for tc in serial.schedule(spec, st, clusters, cal)}
        except Exception as e:  # noqa: BLE001
            assert isinstance(got[i], type(e)), (seed, i, e, got[i])
            continue
        gm = {tc.name: tc.replicas for tc in got[i]}
        assert gm == want, (seed, i)
