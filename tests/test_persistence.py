"""Persistence + restart: the control plane resumes from snapshot + WAL.

Reference analog (SURVEY §5 checkpoint/resume): state in etcd, stateless
components resuming via informer resync.  Here: ObjectStore WAL/snapshot
(store/persistence.py), ControlPlane(persist_dir=...) reload + resync.
"""

from karmada_tpu.e2e import ControlPlane
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DUPLICATED,
    ObjectMeta,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.work import ResourceBinding, Work
from karmada_tpu.store.persistence import load_store
from karmada_tpu.store.store import ObjectStore


def nginx(replicas=3):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "nginx", "namespace": "default"},
        "spec": {"replicas": replicas, "template": {"spec": {"containers": [
            {"name": "c", "resources": {"requests": {"cpu": "100m",
                                                     "memory": "1Gi"}}}]}}},
    }


def dup_policy():
    return PropagationPolicy(
        metadata=ObjectMeta(name="pp", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=Placement(replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)),
        ),
    )


def test_wal_roundtrip(tmp_path):
    d = str(tmp_path / "store")
    store = load_store(d)
    store.create(dup_policy())
    # reload from WAL alone (no explicit snapshot call needed)
    again = load_store(d)
    assert again.get(PropagationPolicy.KIND, "default", "pp").spec.priority == 0
    # delete persists too
    again.delete(PropagationPolicy.KIND, "default", "pp")
    third = load_store(d)
    assert third.try_get(PropagationPolicy.KIND, "default", "pp") is None


def test_snapshot_truncates_wal(tmp_path):
    import os

    d = str(tmp_path / "store")
    store = load_store(d)
    for i in range(20):
        p = dup_policy()
        p.metadata.name = f"pp-{i}"
        store.create(p)
    store.persistence.snapshot()
    assert os.path.getsize(os.path.join(d, "store.wal")) == 0
    again = load_store(d)
    assert len(again.list(PropagationPolicy.KIND)) == 20


def test_resource_version_monotonic_across_restart(tmp_path):
    d = str(tmp_path / "store")
    store = load_store(d)
    obj = store.create(dup_policy())
    rv1 = obj.metadata.resource_version
    again = load_store(d)
    p2 = dup_policy()
    p2.metadata.name = "pp2"
    rv2 = again.create(p2).metadata.resource_version
    assert rv2 > rv1


def test_torn_tail_write_discarded(tmp_path):
    import os

    d = str(tmp_path / "store")
    store = load_store(d)
    store.create(dup_policy())
    wal = os.path.join(d, "store.wal")
    with open(wal, "ab") as f:
        f.write(b"\xff\xff\xff\x7f partial garbage")
    again = load_store(d)  # must not crash; keeps the committed prefix
    assert again.try_get(PropagationPolicy.KIND, "default", "pp") is not None


def test_control_plane_restart_mid_propagation_converges(tmp_path):
    """Kill the plane after scheduling but before the members applied
    anything; a new plane over the same files must converge."""
    d = str(tmp_path / "cp")
    cp = ControlPlane(backend="serial", persist_dir=d)
    cp.add_member("m1")
    cp.add_member("m2")
    cp.tick()
    cp.store.create(dup_policy())
    cp.apply(nginx())
    cp.tick()
    rb = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert len(rb.spec.clusters) == 2
    assert cp.members["m1"].get("Deployment", "default", "nginx") is not None
    # "kill" the plane: drop it entirely; members are fresh (external
    # clusters would have kept state, but convergence must not DEPEND on it)
    del cp

    cp2 = ControlPlane(backend="serial", persist_dir=d)
    cp2.add_member("m1")
    cp2.add_member("m2")
    cp2.tick()
    # restored state is present pre-tick: policy, template, binding, works
    assert cp2.store.try_get(PropagationPolicy.KIND, "default", "pp") is not None
    assert cp2.store.try_get("Deployment", "default", "nginx") is not None
    assert cp2.store.try_get(ResourceBinding.KIND, "default", "nginx-deployment") is not None
    assert len(cp2.store.list(Work.KIND)) >= 2
    # and the propagation pipeline converges onto the new members
    assert cp2.members["m1"].get("Deployment", "default", "nginx") is not None
    assert cp2.members["m2"].get("Deployment", "default", "nginx") is not None


def test_restart_preserves_schedule_result(tmp_path):
    """The scheduler does not churn restored bindings: observed generation
    survives the restart, so an unchanged binding is not rescheduled."""
    d = str(tmp_path / "cp")
    cp = ControlPlane(backend="serial", persist_dir=d)
    cp.add_member("m1")
    cp.tick()
    cp.store.create(dup_policy())
    cp.apply(nginx())
    cp.tick()
    rb1 = cp.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    del cp

    cp2 = ControlPlane(backend="serial", persist_dir=d)
    cp2.add_member("m1")
    cp2.tick()
    rb2 = cp2.store.get(ResourceBinding.KIND, "default", "nginx-deployment")
    assert {tc.name for tc in rb2.spec.clusters} == {tc.name for tc in rb1.spec.clusters}
    assert rb2.status.scheduler_observed_generation == rb2.metadata.generation
