"""Native COO decode (native/decode_fast.c) parity + fallback gates.

Behavior is DEFINED by the Python builder in tensors.decode_compact; the
native pass must be bit-exact against it across mixed routes (device,
spread, big tier), wide Duplicated rows, failure statuses, the explain
outcome plane, and the empty-workload-propagation mode — and the
extension being absent must degrade losslessly to today's behavior.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

import bench
from karmada_tpu import native
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.work import TargetCluster
from karmada_tpu.ops import tensors

pytestmark = pytest.mark.skipif(
    native.load_decode_fast() is None,
    reason=f"decode_fast unavailable: {native.decode_fast_error()}",
)


@pytest.fixture
def no_native_decode(monkeypatch):
    """Force the Python parity control (extension 'absent')."""
    monkeypatch.setattr(native, "_dec_mod", None)
    monkeypatch.setattr(native, "_dec_error", "disabled for parity test")


def _decode_pair(batch, idx, val, status, monkeypatch, **kw):
    """(native result, python-control result) for one COO plane set."""
    assert native.load_decode_fast() is not None
    out_native = tensors.decode_compact(batch, idx, val, status, **kw)
    with monkeypatch.context() as m:
        m.setattr(native, "_dec_mod", None)
        m.setattr(native, "_dec_error", "disabled for parity test")
        out_py = tensors.decode_compact(batch, idx, val, status, **kw)
    return out_native, out_py


def _assert_bit_exact(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        if isinstance(x, Exception) or isinstance(y, Exception):
            assert type(x) is type(y), f"slot {i}: {x!r} vs {y!r}"
            assert getattr(x, "reason", None) == getattr(y, "reason", None)
        else:
            assert x == y, f"slot {i}: {x!r} vs {y!r}"
            for tx, ty in zip(x, y):
                assert type(tx) is type(ty) is TargetCluster
                assert (tx.name, tx.replicas) == (ty.name, ty.replicas)


def _mixed_batch(seed: int, n_clusters: int = 220, n_bindings: int = 512):
    rng = random.Random(seed)
    clusters = bench.build_fleet(rng, n_clusters)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, n_bindings, placements)
    cindex = tensors.ClusterIndex.build(clusters)
    batch = tensors.encode_batch(items, cindex, GeneralEstimator(),
                                 cache=tensors.EncoderCache())
    return batch, items


def _fuzz_coo(batch, seed: int, wide_every: int = 11):
    """Adversarial COO: every route's rows get entries (decode does not
    route-filter), every wide_every-th row is FULL-FLEET wide (the shape
    the old fast path punted to Python), statuses cycle through OK /
    FIT_ERROR / UNSCHEDULABLE / NO_CLUSTER / unknown."""
    rng = random.Random(seed)
    nb, C, nC = batch.n_bindings, batch.C, batch.n_clusters
    idx_l, val_l = [], []
    status = np.zeros(batch.B, np.int32)
    for b in range(nb):
        status[b] = (0, 0, 0, tensors.STATUS_FIT_ERROR,
                     tensors.STATUS_UNSCHEDULABLE, tensors.STATUS_NO_CLUSTER,
                     9)[b % 7]
        if b % wide_every == 0:
            cs = range(nC)  # full-fleet wide row (forces the qsort branch)
        else:
            cs = sorted(rng.sample(range(nC), rng.randint(0, 6)))
        for c in cs:
            idx_l.append(b * C + c)
            val_l.append(rng.choice((0, 0, 1, 2, 7)))
    pad = 32
    idx = np.full(len(idx_l) + pad, -1, np.int32)
    val = np.zeros(len(idx_l) + pad, np.int32)
    idx[:len(idx_l)] = idx_l
    val[:len(val_l)] = val_l
    return idx, val, status


@pytest.mark.parametrize("seed", [7, 23, 101])
def test_parity_fuzz_mixed_routes(seed, monkeypatch):
    batch, items = _mixed_batch(seed)
    idx, val, status = _fuzz_coo(batch, seed)
    for empty_prop in (False, True):
        a, b = _decode_pair(batch, idx, val, status, monkeypatch,
                            items=items,
                            enable_empty_workload_propagation=empty_prop)
        _assert_bit_exact(a, b)


def test_parity_small_fleet_compact_false(monkeypatch):
    """C <= COMPACT_LANES fleets (compact=False) route wide Divided rows
    to the device too — decode parity must hold there as well."""
    batch, items = _mixed_batch(3, n_clusters=12, n_bindings=96)
    idx, val, status = _fuzz_coo(batch, 3, wide_every=5)
    a, b = _decode_pair(batch, idx, val, status, monkeypatch, items=items)
    _assert_bit_exact(a, b)


def test_parity_explain_outcome_plane(monkeypatch):
    """The outcome verdict plane attaches `exc.reason` identically on the
    native and Python paths."""
    from karmada_tpu.obs.decisions import VERDICT_BIT_NAMES

    batch, items = _mixed_batch(11, n_clusters=64, n_bindings=128)
    idx, val, status = _fuzz_coo(batch, 11)
    nb = batch.n_bindings
    outcome = np.zeros(batch.B, np.int32)
    for b in range(nb):
        dom = b % (len(VERDICT_BIT_NAMES) + 1)  # 0 = no rejected clusters
        outcome[b] = int(status[b]) | (dom << 8)
    a, b = _decode_pair(batch, idx, val, status, monkeypatch,
                        items=items, outcome=outcome)
    _assert_bit_exact(a, b)
    assert any(getattr(x, "reason", None) for x in a
               if isinstance(x, Exception)), "fuzz produced no reasons"


def test_absent_extension_falls_back_losslessly(no_native_decode):
    batch, items = _mixed_batch(5, n_clusters=40, n_bindings=64)
    idx, val, status = _fuzz_coo(batch, 5)
    out = tensors.decode_compact(batch, idx, val, status, items=items)
    assert len(out) == batch.n_bindings
    assert all(r is not None for r in out)


def test_ascending_violation_matches_python_assert():
    """Out-of-order COO: the native pass hands back to Python, whose
    assert owns the diagnostic — same failure mode as before."""
    batch, _ = _mixed_batch(9, n_clusters=16, n_bindings=16)
    C = batch.C
    idx = np.array([3 * C + 1, 1 * C + 0, -1], np.int32)  # rows 3 then 1
    val = np.array([1, 1, 0], np.int32)
    status = np.zeros(batch.B, np.int32)
    with pytest.raises(AssertionError, match="row-major"):
        tensors.decode_compact(batch, idx, val, status)


def test_tc_new_guard_reroutes_to_python(monkeypatch):
    """A TargetCluster whose construction stopped being __new__-equivalent
    must silently take the Python builder, never diverge."""
    calls = []
    real = native.load_decode_fast()
    assert real is not None
    monkeypatch.setattr(tensors, "tc_new_is_plain", lambda: False)

    class Spy:
        def decode_coo(self, *a, **k):
            calls.append(1)
            return real.decode_coo(*a, **k)

    monkeypatch.setattr(native, "_dec_mod", Spy())
    batch, items = _mixed_batch(13, n_clusters=16, n_bindings=32)
    idx, val, status = _fuzz_coo(batch, 13)
    out = tensors.decode_compact(batch, idx, val, status, items=items)
    assert not calls, "native path ran despite the guard"
    assert len(out) == batch.n_bindings


def test_native_metric_counts_rows():
    before = tensors.DECODE_NATIVE.value()
    batch, items = _mixed_batch(17, n_clusters=24, n_bindings=48)
    idx, val, status = _fuzz_coo(batch, 17)
    out = tensors.decode_compact(batch, idx, val, status, items=items)
    built = sum(1 for r in out if not isinstance(r, Exception))
    assert tensors.DECODE_NATIVE.value() - before == built


def test_end_to_end_solve_decode_parity(monkeypatch):
    """Through the real jit: solve_compact's d2h views (zero-copy where
    the platform allows) feed the native decode; parity against the
    Python control on the same handle."""
    from karmada_tpu.ops import solver

    batch, items = _mixed_batch(21, n_clusters=10, n_bindings=12)
    res = solver.solve_compact(batch, waves=2)
    idx, val, status = res[0], res[1], res[2]
    a, b = _decode_pair(batch, idx, val, status, monkeypatch, items=items)
    _assert_bit_exact(a, b)
