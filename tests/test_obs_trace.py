"""Flight recorder (karmada_tpu/obs): span propagation across the
pipelined executor's stages and thread handoffs, exactly-once close,
cancelled-cycle completeness, and the zero-allocation disabled path."""

import random
import threading

import pytest

import bench
from karmada_tpu import obs
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.obs.export import (
    latest_pipeline_timeline,
    render_waterfall,
    stage_summary,
)
from karmada_tpu.ops import tensors
from karmada_tpu.scheduler import pipeline


@pytest.fixture
def tracer():
    rec = obs.TRACER.configure(capacity=64, slow_keep=4)
    yield rec
    obs.TRACER.disable()


def _workload(n_bindings=12, n_clusters=24):
    rng = random.Random(0)
    clusters = bench.build_fleet(rng, n_clusters)
    cindex = tensors.ClusterIndex.build(clusters)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, n_bindings, placements)
    return items, cindex, GeneralEstimator()


def _spans_well_formed(tr):
    ids = [s["span_id"] for s in tr["spans"]]
    assert len(ids) == len(set(ids)), "a span closed (recorded) twice"
    by_id = {s["span_id"]: s for s in tr["spans"]}
    for s in tr["spans"]:
        assert s["end_s"] >= s["start_s"] >= 0
        if s["parent_id"] is not None:
            assert s["parent_id"] in by_id, f"orphan span {s['name']}"


# -- core semantics ----------------------------------------------------------

def test_span_closes_exactly_once_and_nests_via_context(tracer):
    with obs.TRACER.span("root") as root:
        with obs.TRACER.span("child"):
            pass
        inner = obs.TRACER.start_span("manual", parent=root)
        inner.end()
        inner.end()  # double close: must not duplicate the record
    (tr,) = tracer.recent()
    _spans_well_formed(tr)
    names = sorted(s["name"] for s in tr["spans"])
    assert names == ["child", "manual", "root"]
    ids = {s["name"]: s["span_id"] for s in tr["spans"]}
    parents = {s["name"]: s["parent_id"] for s in tr["spans"]}
    assert parents["child"] == ids["root"]
    assert parents["manual"] == ids["root"]
    assert parents["root"] is None


def test_root_end_force_closes_open_spans_as_complete_trace(tracer):
    root = obs.TRACER.start_span("r")
    dangling = obs.TRACER.start_span("dangling", parent=root)
    root.end(cancelled=True)
    (tr,) = tracer.recent()
    assert tr["cancelled"] is True
    _spans_well_formed(tr)
    d = next(s for s in tr["spans"] if s["name"] == "dangling")
    assert d["attrs"].get("unfinished") is True
    # a zombie ending the span after finalization is ignored
    dangling.end()
    assert sum(1 for s in tracer.recent()[-1]["spans"]
               if s["name"] == "dangling") == 1
    # ... and a zombie STARTING spans under the finalized trace gets the
    # no-op singleton instead of minting bogus root traces into the ring
    n_before = len(tracer.recent())
    assert obs.TRACER.start_span("late", parent=dangling) is obs.NOOP_SPAN
    obs.TRACER.start_span("late2", parent=dangling).end()
    assert len(tracer.recent()) == n_before


def test_ring_eviction_is_counted_and_slow_shelf_retained(tracer):
    # make one deliberately slow trace, then flood the ring
    slow = obs.TRACER.start_span("slow_root")
    slow.trace._t0 -= 10.0  # noqa: SLF001 — 10s duration without sleeping
    slow.end()
    for i in range(200):
        obs.TRACER.start_span("fast").end()
    assert tracer.dropped > 0, "ring truncation must be counted"
    assert tracer.stats()["recent"] == tracer.capacity
    slowest = tracer.slowest()
    assert slowest and slowest[0]["root"] == "slow_root", (
        "the slowest cycle must survive a ring full of fast ones")
    assert tracer.get(slowest[0]["trace_id"]) is not None


# -- disabled path -----------------------------------------------------------

def test_disabled_tracer_allocates_no_spans():
    assert not obs.TRACER.enabled
    assert obs.TRACER.start_span("x") is obs.NOOP_SPAN
    assert obs.TRACER.span("y", k=1) is obs.NOOP_SPAN
    assert obs.TRACER.attach(None) is obs.NOOP_SPAN
    with obs.TRACER.span("z") as sp:
        assert sp is obs.NOOP_SPAN
    # the worker's dwell stamps stay empty too
    from karmada_tpu.store.worker import AsyncWorker

    w = AsyncWorker("t", lambda k: None)
    w.enqueue("k1")
    assert not w._enqueued_at  # noqa: SLF001
    assert w.process_one()


def test_disabled_pipeline_records_nothing():
    items, cindex, est = _workload(8)
    assert not obs.TRACER.enabled
    res = pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2,
                                carry=True)
    assert res.scheduled > 0
    assert obs.TRACER.recorder is None


# -- pipeline integration ----------------------------------------------------

def test_pipeline_stage_spans_parentage_and_overlap(tracer):
    items, cindex, est = _workload(12)
    res = pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2,
                                carry=True)
    assert res.chunks == 3 and not res.cancelled
    tr = tracer.recent()[-1]
    assert tr["root"] == obs.SPAN_PIPELINE
    _spans_well_formed(tr)
    names = {s["name"] for s in tr["spans"]}
    for stage in obs.PIPELINE_STAGE_SPANS:
        assert stage in names, f"stage {stage} missing"
    by_id = {s["span_id"]: s for s in tr["spans"]}
    cyc = next(s for s in tr["spans"] if s["name"] == obs.SPAN_PIPELINE)
    chunks = sorted((s for s in tr["spans"] if s["name"] == obs.SPAN_CHUNK),
                    key=lambda s: s["attrs"]["index"])
    assert len(chunks) == 3
    for ch in chunks:
        assert ch["parent_id"] == cyc["span_id"]
    # every stage span parents to a chunk wall span
    for s in tr["spans"]:
        if s["name"] in obs.PIPELINE_STAGE_SPANS:
            assert by_id[s["parent_id"]]["name"] == obs.SPAN_CHUNK
    # pipelining: chunk k+1 submits before chunk k finalizes (wall overlap)
    assert chunks[1]["start_s"] < chunks[0]["end_s"]
    # compile-cache attribution: first dispatch misses, later ones hit
    dispatches = [s for s in tr["spans"] if s["name"] == obs.SPAN_DISPATCH]
    caches = {by_id[s["parent_id"]]["attrs"]["index"]:
              s["attrs"].get("compile_cache") for s in dispatches}
    assert caches[0] == "miss" or any(v == "hit" for v in caches.values())
    # the export helpers digest it
    assert "#" in render_waterfall(tr)
    tl = latest_pipeline_timeline(tracer)
    assert tl is not None and obs.SPAN_ENCODE in tl["stages"]
    assert tl["stages"][obs.SPAN_CHUNK]["count"] == 3


def test_pipeline_spans_cross_thread_handoff(tracer):
    """The guarded device cycle runs run_pipeline on a daemon thread; the
    handoff (Tracer.attach) must parent the pipeline spans into the
    calling thread's trace, each closing exactly once."""
    items, cindex, est = _workload(8)
    root = obs.TRACER.start_span("guarded_cycle")

    def run():
        with obs.TRACER.attach(root):
            pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2,
                                  carry=True)

    t = threading.Thread(target=run)
    t.start()
    t.join()
    root.end()
    tr = tracer.recent()[-1]
    assert tr["root"] == "guarded_cycle"
    _spans_well_formed(tr)
    cyc = next(s for s in tr["spans"] if s["name"] == obs.SPAN_PIPELINE)
    root_rec = next(s for s in tr["spans"] if s["name"] == "guarded_cycle")
    assert cyc["parent_id"] == root_rec["span_id"]
    assert any(s["name"] == obs.SPAN_ENCODE for s in tr["spans"])


def test_cancelled_cycle_yields_complete_cancelled_trace(tracer):
    """Mid-pipeline cancellation (the degradation guard's event) still
    produces a finalized trace marked cancelled=true — the evidence the
    guard previously discarded — with every span closed exactly once."""
    items, cindex, est = _workload(12)
    ev = threading.Event()

    def on_chunk(st):
        if st.index == 0:
            ev.set()  # cancel after the first chunk finalizes

    res = pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2,
                                carry=True, cancelled=ev,
                                on_chunk=on_chunk)
    assert res.cancelled
    tr = tracer.recent()[-1]
    assert tr["cancelled"] is True
    _spans_well_formed(tr)
    cyc = next(s for s in tr["spans"] if s["name"] == obs.SPAN_PIPELINE)
    assert cyc["attrs"]["cancelled"] is True
    # chunk 0 finalized normally; a later dispatched-but-abandoned chunk's
    # wall span was force-closed at root end (unfinished marker)
    chunks = {s["attrs"]["index"]: s for s in tr["spans"]
              if s["name"] == obs.SPAN_CHUNK}
    assert 0 in chunks and "unfinished" not in chunks[0]["attrs"]
    assert any("unfinished" in s["attrs"] for s in tr["spans"]
               if s["name"] == obs.SPAN_CHUNK and s is not chunks[0]), (
        "the abandoned in-flight chunk must still appear in the trace")


def test_stage_summary_aggregates(tracer):
    items, cindex, est = _workload(8)
    pipeline.run_pipeline(items, cindex, est, chunk=4, waves=2, carry=True)
    agg = stage_summary(tracer.recent()[-1])
    assert agg[obs.SPAN_ENCODE]["count"] == 2
    assert agg[obs.SPAN_ENCODE]["total_s"] >= agg[obs.SPAN_ENCODE]["max_s"]


# -- satellites: probe history + watcher JSON lines --------------------------

def test_device_probe_history_exported():
    from karmada_tpu.utils import deviceprobe
    from karmada_tpu.utils.metrics import REGISTRY

    def dead_probe(timeout_s):
        return {"ok": False, "platform": None,
                "attempts": [{"ok": False, "s": 1.5, "rc": 1,
                              "err": "tunnel dead"}]}

    deviceprobe.resolve_backend("device", probe=dead_probe)
    last = deviceprobe.last_probe()
    assert last["probed"] and last["ok"] is False
    assert last["elapsed_s"] == 1.5 and last["error"] == "tunnel dead"
    streak = last["consecutive_failures"]
    assert streak >= 1
    deviceprobe.resolve_backend("device", probe=dead_probe)
    assert deviceprobe.last_probe()["consecutive_failures"] == streak + 1
    assert deviceprobe.PROBE_CONSECUTIVE_FAILURES.value() == streak + 1
    assert "karmada_device_probe_consecutive_failures" in REGISTRY.dump()

    def live_probe(timeout_s):
        return {"ok": True, "platform": "tpu",
                "attempts": [{"ok": True, "s": 30.0}]}

    backend, _ = deviceprobe.resolve_backend("device", probe=live_probe)
    assert backend == "device"
    last = deviceprobe.last_probe()
    assert last["ok"] and last["consecutive_failures"] == 0
    assert last["platform"] == "tpu"
    assert deviceprobe.PROBE_LAST_OK.value() == 1.0


def test_watch_bench_probe_records_are_structured_json():
    import json

    import watch_bench

    rec = watch_bench.probe_record(
        {"ok": False, "platform": None,
         "attempts": [{"ok": False, "s": 2.0, "rc": 3, "err": "boom"}]},
        attempt=7)
    line = json.dumps(rec)
    parsed = json.loads(line)
    assert parsed["event"] == "probe" and parsed["attempt"] == 7
    assert parsed["ok"] is False and parsed["rc"] == 3
    assert parsed["elapsed_s"] == 2.0 and "ts" in parsed
    ok_rec = watch_bench.probe_record(
        {"ok": True, "platform": "tpu", "attempts": [{"ok": True, "s": 9.0}]},
        attempt=8)
    assert ok_rec["ok"] is True and ok_rec["platform"] == "tpu"
    assert ok_rec["rc"] is None and ok_rec["err"] is None
