"""The query plane over HTTP: cluster-proxy verbs, search cache
GET/LIST/WATCH, metrics adapter, and karmadactl --server (CLI over TCP).

Reference: pkg/registry/cluster/storage/proxy.go:73 (aggregated proxy
HTTP), pkg/search/proxy (search REST), pkg/metricsadapter (metrics APIs).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from karmada_tpu.search import CACHED_FROM_ANNOTATION
from karmada_tpu.search.httpapi import QueryPlaneServer
from tests.test_query_plane import cp, deployment, dup_policy, registry  # noqa: F401


@pytest.fixture
def served(cp):  # noqa: F811 — pytest fixture chaining
    cp.store.create(registry())
    cp.apply_policy(dup_policy())
    cp.apply(deployment("web"))
    cp.tick()
    srv = QueryPlaneServer(cp.store, cp.members, cp.cluster_proxy,
                           search_cache=cp.search_cache,
                           metrics_provider=cp.metrics_provider)
    url = srv.start()
    yield cp, url
    srv.stop()


def get_json(url, path, subject=None, params=""):
    req = urllib.request.Request(url + path + params)
    if subject:
        req.add_header("X-Karmada-User", subject)
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_proxy_verbs_over_http(served):
    cp, url = served
    # list through the proxy: the Work-applied Deployment is on members
    out = get_json(url, "/clusters/m1/proxy/Deployment")
    assert any(m["metadata"]["name"] == "web" for m in out)
    one = get_json(url, "/clusters/m1/proxy/Deployment/default/web")
    assert one["metadata"]["name"] == "web"
    # pod plane + logs + exec
    pods = get_json(url, "/clusters/m1/proxy/pods")
    assert pods, "admitted replicas must surface as pods"
    pod = pods[0]
    logs = get_json(
        url, f"/clusters/m1/proxy/logs/{pod['namespace']}/{pod['name']}")
    assert isinstance(logs["lines"], list)
    req = urllib.request.Request(
        url + f"/clusters/m1/proxy/exec/{pod['namespace']}/{pod['name']}",
        data=json.dumps({"command": ["env"]}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        out = json.loads(r.read())
    assert out["rc"] == 0


def test_proxy_denies_unknown_subject_over_http(served):
    cp, url = served
    with pytest.raises(urllib.error.HTTPError) as ei:
        get_json(url, "/clusters/m1/proxy/pods", subject="mallory")
    assert ei.value.code == 403


def test_search_cache_and_watch_over_http(served):
    cp, url = served
    objs = get_json(url, "/search/cache/Deployment")
    assert objs, "registry-selected Deployments must be cached"
    assert objs[0]["metadata"]["annotations"][CACHED_FROM_ANNOTATION]

    # WATCH: stream events while a change lands
    events = []

    def consume():
        req = urllib.request.Request(url + "/search/watch?timeout=3")
        with urllib.request.urlopen(req, timeout=10) as r:
            for line in r:
                line = line.strip()
                if line:
                    events.append(json.loads(line))

    t = threading.Thread(target=consume)
    t.start()
    import time

    time.sleep(0.3)
    cp.apply(deployment("web", replicas=5))
    cp.tick()
    t.join(timeout=10)
    assert any(e["object"]["metadata"]["name"] == "web" for e in events)


def test_metrics_adapter_over_http(served):
    cp, url = served
    pods = get_json(url, "/metrics-adapter/pods/Deployment/default/web")
    assert pods and all("usage" in p and "cluster" in p for p in pods)
    cp.metrics_provider.external["queue_depth"] = 42.0
    out = get_json(url, "/metrics-adapter/external/queue_depth")
    assert out["value"] == 42.0
    with pytest.raises(urllib.error.HTTPError) as ei:
        get_json(url, "/metrics-adapter/external/nope")
    assert ei.value.code == 404


def test_control_plane_api_over_http(served):
    cp, url = served
    clusters = get_json(url, "/clusters")
    assert set(clusters) == {"m1", "m2", "m3"}
    table = get_json(url, "/api-table/Cluster")
    assert "NAME" in [h.upper() for h in table["headers"]]
    assert len(table["rows"]) == 3
    rbs = get_json(url, "/api/ResourceBinding")
    assert rbs, "binding manifests listable over HTTP"


def test_cli_over_tcp(served, capsys):
    """karmadactl --server URL: the CLI data-path verbs run over HTTP."""
    from karmada_tpu.cli import main

    cp, url = served
    assert main(["--server", url, "get", "pods", "--cluster", "m1"]) == 0
    out = capsys.readouterr().out
    assert "NAME" in out and "web" in out

    pods = get_json(url, "/clusters/m1/proxy/pods")
    pod = pods[0]
    assert main(["--server", url, "logs", pod["name"],
                 "--cluster", "m1", "-n", pod["namespace"]]) == 0

    assert main(["--server", url, "exec", pod["name"], "--cluster", "m1",
                 "-n", pod["namespace"], "env"]) == 0

    assert main(["--server", url, "top", "clusters"]) == 0
    out = capsys.readouterr().out
    assert "m1" in out

    assert main(["--server", url, "top", "nodes"]) == 0
    out = capsys.readouterr().out
    assert "m1-node-0" in out

    assert main(["--server", url, "top", "pods", "web"]) == 0
    out = capsys.readouterr().out
    assert "web" in out

    assert main(["--server", url, "get", "Deployment", "--cluster", "m1",
                 "-n", "default"]) == 0
    out = capsys.readouterr().out
    assert "web" in out

    # commands that need the local plane refuse politely
    assert main(["--server", url, "join", "m9"]) == 1


def test_remote_apply_and_delete(served, capsys, tmp_path):
    """karmadactl --server apply/delete: control-plane writes over HTTP
    (typed codec + admission run server-side)."""
    import urllib.request

    from karmada_tpu.cli import main

    cp, url = served
    srv_writable = QueryPlaneServer(
        cp.store, cp.members, cp.cluster_proxy,
        search_cache=cp.search_cache,
        metrics_provider=cp.metrics_provider, apply_fn=cp.apply)
    wurl = srv_writable.start()
    try:
        f = tmp_path / "pp.yaml"
        f.write_text("""
apiVersion: policy.karmada.io/v1alpha1
kind: PropagationPolicy
metadata: {name: remote-pp, namespace: default}
spec:
  resourceSelectors:
  - {apiVersion: apps/v1, kind: ConfigMap}
  placement: {}
""")
        assert main(["--server", wurl, "apply", "-f", str(f)]) == 0
        out = capsys.readouterr().out
        assert "PropagationPolicy/remote-pp applied" in out
        pp = cp.store.get("PropagationPolicy", "default", "remote-pp")
        # typed decode + admission defaulting ran server-side
        assert pp.spec.preemption == "Never"
        assert any(t.key == "cluster.karmada.io/not-ready"
                   for t in pp.spec.placement.cluster_tolerations)

        assert main(["--server", wurl, "delete", "PropagationPolicy",
                     "remote-pp", "-n", "default"]) == 0
        assert cp.store.try_get("PropagationPolicy", "default",
                                "remote-pp") is None

        # admission denials surface as errors, not silent writes
        bad = tmp_path / "bad.yaml"
        bad.write_text("""
apiVersion: autoscaling.karmada.io/v1alpha1
kind: FederatedHPA
metadata: {name: bad, namespace: default}
spec:
  scaleTargetRef: {apiVersion: apps/v1, kind: Deployment, name: web}
  minReplicas: 5
  maxReplicas: 2
""")
        assert main(["--server", wurl, "apply", "-f", str(bad)]) == 1

        # the read-only default server refuses writes
        req = urllib.request.Request(
            url + "/api/apply", method="POST",
            data=b'{"kind": "ConfigMap", "metadata": {"name": "x"}}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 403
    finally:
        srv_writable.stop()


def test_remote_write_subject_gating(served, tmp_path):
    """Control-plane writes honor the unified-auth subject, same trust
    root as the proxy verbs."""
    import urllib.request

    cp, _url = served
    srv = QueryPlaneServer(
        cp.store, cp.members, cp.cluster_proxy,
        search_cache=cp.search_cache,
        metrics_provider=cp.metrics_provider,
        apply_fn=cp.apply, auth=cp.unified_auth)
    wurl = srv.start()
    try:
        body = (b'{"apiVersion": "v1", "kind": "ConfigMap", '
                b'"metadata": {"name": "cm1", "namespace": "default"}}')

        def post(subject=None):
            req = urllib.request.Request(
                wurl + "/api/apply", method="POST", data=body,
                headers={"Content-Type": "application/json"})
            if subject:
                req.add_header("X-Karmada-User", subject)
            return urllib.request.urlopen(req, timeout=10)

        with post() as r:  # default subject system:admin is authorized
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(subject="mallory")
        assert ei.value.code == 403
        cp.unified_auth.grant("mallory")
        with post(subject="mallory") as r:
            assert r.status == 200
        # nameless manifests are rejected before any write
        req = urllib.request.Request(
            wurl + "/api/apply", method="POST",
            data=b'{"kind": "ConfigMap"}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        srv.stop()
