"""Golden parity: the C++ serial control vs the Python serial pipeline.

The native control (karmada_tpu/native/serial_solver.cc) must agree with
ops/serial.schedule binding-for-binding — same targets, same failure class —
over the bench scenario mix and adversarial corners (taints, affinities,
scale up/down, fresh reschedule, region spread DFS).  bench.py's
``vs_baseline`` is only honest if this holds.
"""

from __future__ import annotations

import random

import pytest

from karmada_tpu import native
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    EFFECT_NO_SCHEDULE,
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
    Taint,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    StaticClusterWeight,
    Toleration,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial
from karmada_tpu.utils.quantity import Quantity

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native solver unavailable: {native.build_error()}"
)

GVK = ("apps/v1", "Deployment")


def mk_cluster(name, region="", provider="", cpu=32000, mem=128, pods=110,
               taints=(), deleting=False, no_summary=False):
    return Cluster(
        metadata=ObjectMeta(name=name,
                            deletion_timestamp=1.0 if deleting else None),
        spec=ClusterSpec(region=region, provider=provider, taints=list(taints)),
        status=ClusterStatus(
            api_enablements=[APIEnablement(GVK[0], [GVK[1]])],
            resource_summary=None if no_summary else ResourceSummary(
                allocatable={
                    "cpu": Quantity.from_milli(cpu),
                    "memory": Quantity.from_units(mem),
                    "pods": Quantity.from_units(pods),
                },
                allocated={},
            ),
        ),
    )


def mk_binding(name, placement, replicas=10, cpu_m=250, prev=(), uid=None,
               fresh=False):
    spec = ResourceBindingSpec(
        resource=ObjectReference(
            api_version=GVK[0], kind=GVK[1], namespace="default",
            name=name, uid=uid or f"uid-{name}",
        ),
        replicas=replicas,
        replica_requirements=ReplicaRequirements(
            resource_request={"cpu": Quantity.from_milli(cpu_m)}
        ),
        placement=placement,
        clusters=[TargetCluster(name=n, replicas=r) for n, r in prev],
        reschedule_triggered_at=100.0 if fresh else None,
    )
    return spec, ResourceBindingStatus()


def assert_parity(items, clusters):
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    snap = native.NativeSnapshot(clusters, native.collect_res_names(items))
    got = native.schedule_batch_native(items, snap)
    for (spec, status), (st, targets) in zip(items, got):
        assert st != native.STATUS_UNSUPPORTED, (
            f"{spec.resource.name}: unexpectedly unsupported"
        )
        try:
            want = serial.schedule(spec, status, clusters, cal)
            want_d = {tc.name: tc.replicas for tc in want}
            want_st = native.STATUS_OK
        except serial.FitError:
            want_d, want_st = {}, native.STATUS_FIT_ERROR
        except serial.UnschedulableError:
            want_d, want_st = {}, native.STATUS_UNSCHEDULABLE
        except serial.NoClusterAvailableError:
            want_d, want_st = {}, native.STATUS_NO_CLUSTER
        got_d = {tc.name: tc.replicas for tc in targets}
        assert st == want_st, (spec.resource.name, st, want_st)
        if st == native.STATUS_OK:
            assert got_d == want_d, (spec.resource.name, got_d, want_d)


def test_bench_mix_parity():
    import bench

    rng = random.Random(7)
    clusters = bench.build_fleet(rng, 96)
    placements = bench.build_placements(rng, [c.name for c in clusters])
    items = bench.build_bindings(rng, 384, placements)
    assert_parity(items, clusters)


def test_taints_affinity_and_static_weights():
    taint = Taint(key="maintenance", value="true", effect=EFFECT_NO_SCHEDULE)
    clusters = [
        mk_cluster("m-a", region="r1"),
        mk_cluster("m-b", region="r1", taints=[taint]),
        mk_cluster("m-c", region="r2"),
        mk_cluster("m-d", region="r2", deleting=True),
        mk_cluster("m-e", region="", no_summary=True),
    ]
    tolerate = Toleration(key="maintenance", operator="Exists")
    items = [
        mk_binding("tainted", Placement(
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            ))),
        mk_binding("tolerated", Placement(
            cluster_tolerations=[tolerate],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            ))),
        mk_binding("affinity", Placement(
            cluster_affinity=ClusterAffinity(cluster_names=["m-a", "m-c"]),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED),
        ), replicas=3),
        mk_binding("static-weighted", Placement(
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(static_weight_list=[
                    StaticClusterWeight(
                        target_cluster=ClusterAffinity(cluster_names=["m-a"]),
                        weight=3),
                    StaticClusterWeight(
                        target_cluster=ClusterAffinity(cluster_names=["m-c"]),
                        weight=1),
                ]),
            )), replicas=8),
        mk_binding("no-fit", Placement(
            cluster_affinity=ClusterAffinity(cluster_names=["absent"]),
        ), replicas=2),
    ]
    assert_parity(items, clusters)


def test_scale_paths_and_fresh():
    clusters = [mk_cluster(f"m-{i}", region=f"r{i % 3}", cpu=64000, pods=200)
                for i in range(12)]
    dyn = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
        ))
    agg = Placement(
        replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED,
        ))
    items = [
        # steady scale-up: prev 6 -> want 20
        mk_binding("up", dyn, replicas=20, prev=[("m-1", 3), ("m-2", 3)]),
        # steady scale-down: prev 30 -> want 10
        mk_binding("down", dyn, replicas=10,
                   prev=[("m-0", 10), ("m-3", 12), ("m-5", 8)]),
        # equality: no-op
        mk_binding("same", dyn, replicas=6, prev=[("m-1", 2), ("m-2", 4)]),
        # fresh reassignment ignores steady mode
        mk_binding("fresh", dyn, replicas=9, prev=[("m-7", 9)], fresh=True),
        # aggregated prefers prior clusters via resort
        mk_binding("agg-up", agg, replicas=14, prev=[("m-4", 4)]),
    ]
    assert_parity(items, clusters)


def test_region_spread_dfs_parity():
    rng = random.Random(3)
    clusters = [
        mk_cluster(f"m-{i:02d}", region=f"r{i % 5}", cpu=rng.randint(8000, 64000),
                   pods=rng.randint(30, 200))
        for i in range(30)
    ]
    items = []
    for i in range(24):
        rmin = rng.randint(1, 2)
        p = Placement(
            spread_constraints=[
                __import__("karmada_tpu.models.policy", fromlist=["SpreadConstraint"]).SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_REGION,
                    min_groups=rmin, max_groups=rng.randint(rmin, 4)),
                __import__("karmada_tpu.models.policy", fromlist=["SpreadConstraint"]).SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                    min_groups=2, max_groups=rng.randint(2, 8)),
            ],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            ),
        )
        items.append(mk_binding(f"spread-{i}", p,
                                replicas=rng.choice([3, 10, 40])))
    assert_parity(items, clusters)


def test_unsupported_marked_not_wrong():
    """Multi-component bindings and vanished prev clusters must surface as
    STATUS_UNSUPPORTED (serial-only classes), never as a wrong answer."""
    clusters = [mk_cluster("m-a"), mk_cluster("m-b")]
    spec, status = mk_binding("vanished", Placement(), replicas=5,
                              prev=[("gone", 5)])
    snap = native.NativeSnapshot(clusters, ["cpu"])
    got = native.schedule_batch_native([(spec, status)], snap)
    assert got[0][0] == native.STATUS_UNSUPPORTED


def test_non_workload_zero_propagation_parity():
    """ConfigMap-style bindings (replicas=0, no requirements) must propagate
    to ALL candidates with zero replicas, exactly like assign_replicas'
    early return (core/common.go:44-78)."""
    clusters = [mk_cluster("m-a"), mk_cluster("m-b"), mk_cluster("m-c")]
    spec = ResourceBindingSpec(
        resource=ObjectReference(api_version=GVK[0], kind=GVK[1],
                                 namespace="default", name="cm", uid="u-cm"),
        replicas=0,
        placement=Placement(),
    )
    items = [(spec, ResourceBindingStatus())]
    est = GeneralEstimator()
    cal = serial.make_cal_available([est])
    want = serial.schedule(spec, items[0][1], clusters, cal)
    snap = native.NativeSnapshot(clusters, [])
    st, got = native.schedule_batch_native(items, snap)[0]
    assert st == native.STATUS_OK
    assert {t.name: t.replicas for t in got} == {t.name: t.replicas for t in want}
    assert {t.replicas for t in got} == {0} and len(got) == 3
